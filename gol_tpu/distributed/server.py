"""Engine server — the TPU-side half of the distributed split.

The reference spec's topology is controller ⇄ engine over the network,
with the engine running headless "on AWS" and controllers attaching and
detaching at will (ref: README.md:157-233; the committed code has only
dead stubs, ref: gol/distributor.go:44-52,459-530). This server is that
capability, working:

- owns the Engine (device turn loop) and keeps it evolving whether or
  not a controller is attached — the fault story's first half
  (SURVEY.md §5: "engine keeps evolving without a controller");
- accepts ONE DRIVING controller at a time over TCP, plus any number
  of read-only OBSERVERS (hello role:"observe" — r5 multi-observer
  serving: the broadcaster already fans out one event stream, and only
  steering verbs need arbitration); on attach each peer gets a full
  board sync (the role of the commented GetCurrentBoard RPC,
  ref: gol/distributor.go:489-498) and then the event stream;
- per-turn CellFlipped diffs are streamed only while a controller that
  asked for them is attached (`hello.want_flips`) — flips-off engines
  run the chunked fast path, so a detached engine pays zero event tax;
- verbs: 'p'/'s' forwarded to the engine; 'q' detaches the controller
  and the engine lives on (ref: README.md:182); 'k' shuts the whole
  system down after a final snapshot (ref: README.md:183);
- `resume_from` boots the engine from an out/<W>x<H>x<T>.pgm snapshot,
  continuing at turn T — PGM-out + PGM-in checkpoint/resume
  (SURVEY.md §5);
- liveness (docs/RESILIENCE.md): a heartbeat thread beacons every
  attached peer whose stream has idled past `heartbeat_secs` (so a
  client behind a 40s cold compile still sees a live link), and evicts
  hb-capable peers that stop answering — the failure detector the
  30s send timeout alone could never be (a dead-but-open peer that
  never receives anything would hold its slot forever).
"""

from __future__ import annotations

import contextlib
import hmac
import itertools
import json
import logging
import queue
import socket
import threading
import time
from typing import Optional

import numpy as np

from gol_tpu import obs
from gol_tpu.checkpoint import snapshot_turn
from gol_tpu.obs import accounting, flight, tracing
from gol_tpu.obs.freshness import ServerFreshness
from gol_tpu.distributed import wire
from gol_tpu.relay.writerpool import PoolFull, WriterPool
from gol_tpu.engine.distributor import Engine
from gol_tpu.events import (
    BoardSync,
    CellFlipped,
    FinalTurnComplete,
    FlipBatch,
    FlipChunk,
    TurnComplete,
)
from gol_tpu.io.pgm import read_pgm
from gol_tpu.params import Params
from gol_tpu.analysis.concurrency import lockcheck

__all__ = ["EngineServer", "SessionServer", "snapshot_turn"]

log = logging.getLogger(__name__)


class _ServerMetrics:
    """Registry handles for the serving plane (gol_tpu.obs) — resolved
    once; all increments are host-side, per connection event or per
    wire frame (never per cell). Catalog: docs/OBSERVABILITY.md."""

    def __init__(self):
        self.accepts = obs.counter(
            "gol_tpu_server_accepts_total", "TCP connections accepted"
        )
        self.rejects = {
            r: obs.counter(
                "gol_tpu_server_rejects_total",
                "Attaches rejected by reason", {"reason": r},
            ) for r in ("bad-hello", "unauthorized", "busy",
                        "at-capacity", "draining")
        }
        self.attaches = {
            r: obs.counter(
                "gol_tpu_server_attaches_total",
                "Peers attached by role", {"role": r},
            ) for r in ("drive", "observe")
        }
        self.detaches = obs.counter(
            "gol_tpu_server_detaches_total", "Peers detached (any cause)"
        )
        self.events = obs.counter(
            "gol_tpu_server_broadcast_events_total",
            "Engine events consumed by the broadcaster",
        )
        self.frames = obs.counter(
            "gol_tpu_server_frames_total", "Wire frames enqueued to peers"
        )
        self.frame_bytes = obs.counter(
            "gol_tpu_server_frame_bytes_total",
            "Wire payload bytes enqueued to peers (pre-framing)",
        )
        self.queue_depth = obs.gauge(
            "gol_tpu_server_writer_queue_depth",
            "Deepest per-peer writer queue at the last flush",
        )
        self.overflows = obs.counter(
            "gol_tpu_server_queue_overflows_total",
            "Peers evicted after staying wedged past the drain deadline",
        )
        self.degradations = obs.counter(
            "gol_tpu_server_degradations_total",
            "Peers entering degraded (frame-shedding) mode at the "
            "writer-queue high-water mark",
        )
        self.recoveries = obs.counter(
            "gol_tpu_server_degraded_recoveries_total",
            "Degraded peers resynced via a coalesced BoardSync after "
            "their queue drained",
        )
        self.shed_frames = obs.counter(
            "gol_tpu_server_shed_frames_total",
            "Stream frames shed instead of enqueued to degraded peers",
        )
        self.peers = obs.gauge(
            "gol_tpu_server_peers", "Currently attached peers"
        )
        self.heartbeats = obs.counter(
            "gol_tpu_server_heartbeats_total",
            "Liveness beacons sent into idle peer streams",
        )
        self.batch_turns = obs.histogram(
            "gol_tpu_server_batch_turns",
            "Turns carried per encoded k-turn flip-batch wire frame "
            "(hello \"batch\" peers)",
        )
        self.evicted = obs.counter(
            "gol_tpu_server_peer_evicted_total",
            "Peers evicted for missing the heartbeat deadline",
        )
        self.chunks = obs.counter(
            "gol_tpu_server_broadcast_chunks_total",
            "k-turn FlipChunk events fanned out by the broadcaster",
        )
        self.chunk_encodes = obs.counter(
            "gol_tpu_server_chunk_encodes_total",
            "FBATCH encode passes (one per chunk per distinct "
            "negotiated max-k — encode-once fan-out means this tracks "
            "chunks, not chunks x peers; the relay smoke's gate)",
        )


_METRICS = _ServerMetrics()


#: Labeled children the per-peer lag family exposes before collapsing
#: the rest into an {peer="other"} aggregate — at relay-scale peer
#: counts one labeled series per connection would be a scrape-payload
#: and registry-cardinality problem, and nobody reads the 400th-worst
#: peer's lag anyway.
PEER_LAG_TOPK = 16


def _lag_family() -> "obs.TopKGauge":
    return obs.registry().topk_gauge(
        "gol_tpu_server_peer_lag_frames",
        "Writer-queue depth (frames behind) per attached peer — "
        "bounded exposition: top-K worst labeled, the rest one "
        "'other' aggregate; children evicted at detach",
        label="peer", cap=PEER_LAG_TOPK,
    )


class _LagHandle:
    """Per-connection view onto the bounded lag family: .set() like
    the old per-peer Gauge, so every call site is unchanged."""

    __slots__ = ("_family", "_child")

    def __init__(self, family, child: str):
        self._family = family
        self._child = child

    def set(self, v: float) -> None:
        self._family.set_child(self._child, v)

    def remove(self) -> None:
        self._family.remove_child(self._child)


#: Every per-peer labeled family is declared to the shared
#: entity-eviction helper (obs.registry): teardown calls ONE
#: `evict_entity("peer", token)` instead of remembering each family,
#: so a new per-peer series added later inherits eviction by
#: declaring itself here-adjacent rather than patching every detach
#: path (the bounded-cardinality audit, docs/OBSERVABILITY.md).
obs.track_entity_series("peer", "gol_tpu_server_peer_lag_frames",
                        topk=True)


def install_lag_gauge(conn: "_Conn") -> None:
    """Per-peer backpressure visibility: how many frames behind this
    peer's writer queue is. Bounded-cardinality discipline: children
    key on the connection token inside ONE TopKGauge entry (top-K
    worst labeled + an 'other' aggregate), and `remove_lag_gauge`
    evicts the child at detach, so both the registry and the
    exposition stay bounded under churn."""
    conn.lag_metric = _LagHandle(_lag_family(), str(conn.token))


def remove_lag_gauge(conn: "_Conn") -> None:
    if conn.lag_metric is not None:
        obs.evict_entity("peer", conn.token)
    conn.lag_metric = None


def _forget_peer_usage(conn: "_Conn") -> None:
    """Evict a detached peer's usage series (accounting plane). Only
    peer-scoped principals go: a session-attached connection bills to
    its TENANT, whose usage outlives any one socket — the manager
    forgets it at destroy/park."""
    m = accounting.meter()
    if m is not None and conn.principal.startswith("peer:"):
        m.forget(conn.principal)


class _Conn:
    """One attached controller: socket + send lock + subscription mode."""

    _next_token = itertools.count(1).__next__  # only the accept thread draws

    #: Writer-flush budget for interactive paths that finish ONE peer
    #: (the 'q' detach ack) rather than draining the whole set — the
    #: same order as DRAIN_TIMEOUT, not the old 30s that let a single
    #: wedged writer stall a detach for half a minute.
    FINISH_TIMEOUT = 5.0
    #: Per-direction socket deadline. Sends: a stalled-but-open
    #: controller (SIGSTOP, dead network path) fills its TCP window and
    #: would otherwise block the writer's sendall forever. Reads: the
    #: reader wakes at this cadence (an idle expiry at a frame boundary
    #: is clean — see wire.recv_msg) instead of blocking unboundedly,
    #: so every blocking read in this package carries a deadline (the
    #: blocking-io-timeout analysis check). Deliberately NOT the (much
    #: shorter) eviction deadline: eviction is the heartbeat thread's
    #: judgement from the last_rx clock — a tight deadline here would
    #: also bound sends and could kill a slow-but-alive peer mid
    #: board-sync.
    IO_TIMEOUT = 30.0

    #: Writer-queue depth at which a peer is DEGRADED (stream frames
    #: shed, coalesce-to-BoardSync on drain) instead of declared dead
    #: (docs/RESILIENCE.md "Overload & degradation"). Well under
    #: QUEUE_DEPTH so control frames (the coalesced sync, byes) always
    #: have room while a peer is shedding.
    HIGH_WATER = 256
    #: Queue depth at/below which a degraded peer counts as drained:
    #: the broadcaster coalesces everything it missed into one fresh
    #: BoardSync (synced_turn-gated, so nothing double-applies).
    LOW_WATER = 8

    #: Seconds a degraded peer may stay wedged (queue above LOW_WATER)
    #: before it is evicted — the only overflow-eviction left; a peer
    #: that drains inside the deadline is resynced instead.
    DRAIN_SECS = 10.0

    #: Hard cap on a peer's outbound queue, in frames — the control
    #: plane's headroom above high_water lives under it (see _enqueue).
    QUEUE_DEPTH = 1024

    def __init__(self, sock: socket.socket, want_flips: bool,
                 compact: bool = False, binary: bool = False,
                 levels: bool = False, role: str = "drive",
                 hb: bool = False, delta: bool = False,
                 batch: int = 0,
                 io_timeout: Optional[float] = None,
                 high_water: Optional[int] = None,
                 drain_secs: Optional[float] = None,
                 pool: Optional[WriterPool] = None):
        #: "drive" (exclusive slot, verbs accepted) or "observe"
        #: (read-only: BoardSync + events, verbs rejected) — r5
        #: multi-observer serving (VERDICT r4 next #7).
        self.role = role
        self.sock = sock
        sock.settimeout(io_timeout if io_timeout is not None
                        else self.IO_TIMEOUT)
        #: Peer advertised heartbeat support in its hello: it answers
        #: our beacons with {"t":"hb"} pongs, so silence past the
        #: eviction deadline means the peer is dead, not just quiet —
        #: only such peers are ever evicted (a legacy controller that
        #: sends one verb an hour keeps its slot, as before).
        self.hb = hb
        now = time.monotonic()
        #: Last byte received from / enqueued to this peer, and how
        #: many beacons went unanswered since last_rx — the liveness
        #: state the heartbeat thread reads (GIL-atomic scalar writes;
        #: reader and heartbeat threads never lock against each other).
        self.last_rx = now
        self.last_tx = now
        self.hb_unanswered = 0
        self.want_flips = want_flips
        #: Peer advertised the zlib'd-int32 flips encoding in its hello;
        #: older controllers get legacy JSON pair lists (the skew the
        #: serve/connect split exists for runs both ways).
        self.compact = compact
        #: Peer advertised raw binary frames (tag + header + zlib) for
        #: the bulk plane — flips, board syncs, final alive sets ride
        #: without the base64-inside-JSON inflation (~33% on a
        #: link-bound watched run, VERDICT r4 Weak #4).
        self.binary = binary
        #: Peer advertised the delta-of-sparse flips frames (r6): each
        #: two-state turn rides as changed-word XOR masks with the
        #: changed-word bitmap delta'd against the previous sent turn
        #: (wire.delta_flips_to_frame). Binary-only; `delta_prev` is
        #: the chain state — the bitmap of the last SENT turn, reset to
        #: None at every BoardSync so reattach/resync restarts the
        #: chain on both ends.
        self.delta = delta and binary
        self.delta_prev = None
        #: Negotiated k-turn batch frames (hello "batch", r10): the
        #: clamped max turns one _TAG_FBATCH frame may carry to this
        #: peer, 0 = per-turn frames. Binary-only, like delta, and
        #: flips-only — a flip-less watcher can never receive a batch
        #: frame, so honoring its "batch" key would flip the engine
        #: into chunk emission (and burstier delivery for everyone)
        #: for nothing. Batch frames are SELF-CONTAINED (the turn-axis
        #: delta chain never crosses a frame), so no chain state lives
        #: here.
        self.batch = batch if (binary and want_flips) else 0
        #: Peer can apply per-cell gray levels (multi-state batches,
        #: r5). Without it, level batches downgrade to plain flips —
        #: a pre-r5 peer must keep receiving frames it understands
        #: rather than ignorable unknown tags (a silently frozen
        #: display).
        self.levels = levels
        #: Matches this connection to the BoardSync it requested.
        self.token = _Conn._next_token()
        #: Accounting principal every resource this conn spends is
        #: attributed to (gol_tpu.obs.accounting): peer-token by
        #: default; the SessionServer re-points it at the session id
        #: once the peer attaches one.
        self.principal = f"peer:{self.token}"
        # No events flow until this connection's BoardSync has been sent:
        # a controller's first message is always the board state, never a
        # TurnComplete it has no context for.
        self.synced = False
        #: Turn of the BoardSync this peer last received. Buffered flips
        #: for any turn <= this are ALREADY IN the synced board — the
        #: broadcaster must not flush them to this peer, or an XOR
        #: consumer double-applies them (ADVICE r5 #1: the multi-peer
        #: rewrite dropped the old 'flips = []' reset, and a global
        #: reset would be wrong now anyway — OTHER synced peers are
        #: still owed those flips).
        self.synced_turn = -1
        self._lock = lockcheck.make_lock("_Conn._lock")
        # Outbound frames ride a bounded per-connection queue: on the
        # WRITER POOL (gol_tpu.relay.writerpool — the default for both
        # servers and the relay tier: thousands of non-blocking
        # sockets per event-loop thread) when `pool` is given, else
        # drained by this connection's own writer thread (the legacy
        # embedder path). Either way the broadcaster fans out wait-
        # free: a single wedged peer (SIGSTOP, blackholed path) can
        # only fill its own bounded queue, never stall another peer's
        # stream, and a peer more than QUEUE_DEPTH frames behind is
        # declared dead without blocking anyone.
        QUEUE_DEPTH = self.QUEUE_DEPTH
        self._pool = pool
        self._handle = None  # PoolHandle once start_writer ran (pooled)
        self._out: "queue.Queue[bytes | None]" = queue.Queue(QUEUE_DEPTH)
        self._dead = threading.Event()
        self._writer: Optional[threading.Thread] = None
        #: Slow-consumer degradation state (docs/RESILIENCE.md
        #: "Overload & degradation"): once the writer queue crosses
        #: `high_water`, stream frames (flips, turn events, beacons)
        #: are SHED wait-free instead of killing the peer; when the
        #: queue drains to LOW_WATER the server coalesces the missed
        #: backlog into one BoardSync, and only a peer still wedged
        #: past the server's drain deadline is evicted.
        # Clamped both ways: at least one frame of band above
        # LOW_WATER (a mark at/below the drain level would re-enter
        # degradation the instant it recovers — a permanent
        # degrade/resync thrash loop sending a full BoardSync per
        # turn), and 64 frames of control-plane headroom under the
        # queue's hard cap.
        self.high_water = max(
            self.LOW_WATER + 1,
            min(QUEUE_DEPTH - 64,
                high_water if high_water is not None
                else self.HIGH_WATER),
        )
        self.drain_secs = (drain_secs if drain_secs is not None
                           else self.DRAIN_SECS)
        self.degraded = False
        self.degraded_since = 0.0
        #: One drain-deadline eviction = ONE overflow count, whichever
        #: side (broadcaster's offer_stream or the heartbeat judge)
        #: notices first — bench_compare gates on this counter moving
        #: off zero, so a double-counted eviction skews the gate. Own
        #: lock: `_lock` is held across blocking socket writes, and the
        #: tally must stay wait-free for the broadcaster.
        self._ovf_counted = False
        self._ovf_lock = lockcheck.make_lock("_Conn._ovf_lock")
        #: A coalescing BoardSync has been requested/enqueued for this
        #: peer and has not arrived yet — don't request another.
        self.resync_pending = False
        #: Replay-plane scrub state (gol_tpu.replay, docs/REPLAY.md):
        #: a peer parked at a seek position. While set, the live /
        #: broadcast stream is withheld (frames past the seeked board
        #: would XOR garbage onto it); {"t":"seek","turn":"live"}
        #: resyncs and clears it. `seek_gate` orders the toggle + the
        #: served historical frames against concurrent stream sends
        #: (RLock: the drain-recovery path resyncs from inside a gated
        #: callback).
        self.scrub = False
        self.seek_gate = lockcheck.make_rlock("_Conn.seek_gate")
        #: Per-peer lag gauge (label evicted at detach) — installed by
        #: the server once the peer is attached.
        self.lag_metric = None
        #: Freshness plane (gol_tpu.obs.freshness): the last turn
        #: WRITTEN to this peer — stamped at every successful stream
        #: send/sync, read by the owning server's ServerFreshness
        #: sweep to turn "peer is at turn T" into seconds of turn age.
        #: Shed frames deliberately do not advance it: a degraded
        #: peer's growing age IS the signal the alert plane watches.
        self.fresh_turn = -1

    def note_written(self, turn: int) -> None:
        """Advance the freshness stamp (monotone)."""
        if turn > self.fresh_turn:
            self.fresh_turn = turn

    def mark_degraded(self) -> None:
        if self.degraded:
            return
        self.degraded = True
        self.degraded_since = time.monotonic()
        self.resync_pending = False
        _METRICS.degradations.inc()
        log.warning(
            "peer %d writer queue crossed high-water (%d frames): "
            "degrading (shedding stream frames, will coalesce to a "
            "BoardSync on drain)", self.token, self.high_water,
        )
        tracing.event("server.degrade", "lifecycle", role=self.role,
                      token=self.token, queued=self.queued())
        flight.note("server.degrade", role=self.role, token=self.token)

    def mark_recovered(self) -> None:
        """A coalescing BoardSync just went out: the peer's stream is
        whole again (synced_turn gates anything still in flight)."""
        if not self.degraded:
            return
        self.degraded = False
        self.resync_pending = False
        _METRICS.recoveries.inc()
        tracing.event("server.degrade_recovered", "lifecycle",
                      role=self.role, token=self.token)
        flight.note("server.degrade_recovered", token=self.token)

    def offer_stream(self) -> bool:
        """Gate ONE stream-plane frame (flips, turn events, beacons):
        True = send it, False = shed it (the peer is degraded — the
        coalescing BoardSync will make it whole on drain). Called
        BEFORE encoding, so a shed frame never advances per-peer
        encoder state (a delta peer's chain must only move on frames
        that actually ship). Degradation entry happens here, wait-free,
        on the broadcaster's thread; a degraded peer still wedged
        (queue above LOW_WATER) past `drain_secs` is the one overflow
        case left — declared dead exactly like the old queue-full
        death, without ever blocking the broadcaster."""
        if not self.writer_started:
            return True  # pre-attach: nothing to shed yet
        if not self.degraded:
            if self.queued() < self.high_water:
                return True
            self.mark_degraded()
        _METRICS.shed_frames.inc()
        if (time.monotonic() - self.degraded_since > self.drain_secs
                and self.queued() > self.LOW_WATER):
            self._dead.set()
            if self.count_overflow():
                _METRICS.overflows.inc()
            raise wire.WireError(
                "peer wedged past the drain deadline"
            )
        return False

    def count_overflow(self) -> bool:
        """Test-and-set the overflow tally for this peer: True exactly
        once, however many threads (broadcaster, heartbeat judge)
        declare the same drain-deadline eviction."""
        with self._ovf_lock:
            if self._ovf_counted:
                return False
            self._ovf_counted = True
            return True

    def drained(self) -> bool:
        """A degraded peer whose writer queue has drained to LOW_WATER
        is ready for its coalescing BoardSync."""
        return (self.degraded and not self.resync_pending
                and self.queued() <= self.LOW_WATER)

    @property
    def writer_started(self) -> bool:
        """Post-handshake: frames queue instead of sending directly
        (the old `_writer is not None` test, pool-aware)."""
        return self._writer is not None or self._handle is not None

    def queued(self) -> int:
        """Frames pending in this peer's writer queue — the number the
        degradation thresholds (high_water / LOW_WATER) gate on,
        whichever backend drains it."""
        if self._handle is not None:
            return self._handle.qsize()
        return self._out.qsize()

    def _wrap(self, payload: bytes) -> bytes:
        """Frame one payload for this peer's transport (the writer
        pool queues fully-framed bytes). The WS gateway's conns
        override this with RFC-6455 binary framing."""
        return wire.frame_bytes(payload)

    def _send_now(self, payload: bytes) -> None:
        """Blocking direct send on the caller's thread (pre-attach
        handshake replies only) — transport-framed, serialized against
        everything else by `_lock`. Emits the same per-frame
        `wire.send` mark as every other send path, so handshake
        replies don't vanish from merged timelines."""
        with self._lock:
            self.sock.sendall(self._wrap(payload))
        tracing.event("wire.send", "wire", bytes=len(payload))

    def start_writer(self, on_error) -> None:
        """Begin queue-drained sending; `on_error(conn)` fires (from
        the pool's loop thread, or the legacy writer thread) when the
        peer's socket fails."""
        if self._pool is not None:
            try:
                self._handle = self._pool.register(
                    self.sock,
                    on_error=lambda _h: (self._dead.set(),
                                         on_error(self)),
                    max_frames=self.QUEUE_DEPTH,
                )
            except RuntimeError:
                # Pool already closed (attach racing shutdown): the
                # peer is as dead as its server — surface the wire
                # error the accept paths already handle.
                self._dead.set()
                raise wire.WireError("writer pool is closed") from None
            return
        self._writer = threading.Thread(
            target=self._write_loop, args=(on_error,),
            name="gol-conn-writer", daemon=True,
        )
        self._writer.start()

    def _write_loop(self, on_error) -> None:
        while True:
            payload = self._out.get()
            if payload is None:
                return
            try:
                with self._lock:
                    wire.send_frame(self.sock, payload)
            except (wire.WireError, OSError):
                self._dead.set()
                on_error(self)
                return

    def _enqueue(self, payload: bytes) -> None:
        """Queue one frame for the writer. The stream plane gates
        itself through `offer_stream` FIRST, so a degraded peer only
        sees control frames (handshake replies, the coalescing
        BoardSync, farewells) here — those always enqueue, and
        high_water sits well under QUEUE_DEPTH precisely so they have
        room. A peer so far gone that even the control plane overflows
        the full QUEUE_DEPTH is declared dead."""
        if self._dead.is_set():
            raise wire.WireError("peer is gone")
        self.last_tx = time.monotonic()
        _METRICS.frames.inc()
        _METRICS.frame_bytes.inc(len(payload))
        # Accounting plane: wire bytes attributed at the ONE choke
        # point every tier's sends pass through (EngineServer,
        # SessionServer, relay, WS conns all enqueue here).
        accounting.charge(self.principal, wire_bytes=len(payload))
        if not self.writer_started:
            # Pre-attach (handshake replies): direct, no queue yet.
            self._send_now(payload)
            return
        if self._handle is not None:
            try:
                self._handle.enqueue(self._wrap(payload))
            except BrokenPipeError:
                self._dead.set()
                raise wire.WireError("peer is gone") from None
            except PoolFull:
                # Even the shedding headroom is gone (control frames
                # past the full queue bound): declare the peer dead
                # without ever blocking the broadcaster.
                self._dead.set()
                if self.count_overflow():
                    _METRICS.overflows.inc()
                raise wire.WireError("peer send queue overflow") \
                    from None
            return
        try:
            self._out.put_nowait(payload)
        except queue.Full:
            self._dead.set()
            if self.count_overflow():
                _METRICS.overflows.inc()
            raise wire.WireError("peer send queue overflow") from None

    def send(self, msg: dict) -> None:
        self._enqueue(json.dumps(msg, separators=(",", ":")).encode())

    def send_direct(self, msg: dict) -> None:
        """Send NOW, bypassing the writer queue (still serialized with
        it — the queue's writer holds the same per-frame lock, so
        frames never interleave). For the clock-probe echo ONLY: its
        whole value is a prompt turnaround, and queueing it behind a
        burst of flip frames would smuggle the backlog delay into the
        client's RTT/offset estimate. Stream-ordering-sensitive
        messages must keep using send()."""
        payload = json.dumps(msg, separators=(",", ":")).encode()
        _METRICS.frames.inc()
        _METRICS.frame_bytes.inc(len(payload))
        accounting.charge(self.principal, wire_bytes=len(payload))
        if self._handle is not None:
            # Pool mode: jump the backlog instead of bypassing the
            # queue — the pool serializes the socket, so a true bypass
            # could interleave into a frame mid-send. Front placement
            # keeps the turnaround prompt (nothing queued overtakes
            # it), which is the whole point of the probe echo.
            with contextlib.suppress(BrokenPipeError, PoolFull):
                self._handle.enqueue(self._wrap(payload), front=True)
            return
        with self._lock:
            wire.send_frame(self.sock, payload)

    def send_raw(self, payload: bytes) -> None:
        self._enqueue(payload)

    def request_finish(self) -> None:
        """Enqueue the writer's exit sentinel without waiting — the
        writer drains everything already queued (including a farewell)
        and then exits. Pair with `join_writer`; `_drain_conns` fans
        the sentinels out to every peer FIRST so wedged writers drain
        concurrently instead of serializing shutdown."""
        if self._handle is not None:
            self._handle.request_finish()
            return
        if self._writer is None:
            return
        with contextlib.suppress(queue.Full):
            self._out.put_nowait(None)

    def join_writer(self, timeout: float) -> None:
        if self._handle is not None:
            self._handle.join(timeout)
        elif self._writer is not None:
            self._writer.join(timeout)

    def finish(self, timeout: Optional[float] = None) -> None:
        """Flush the outbound queue (writer drains everything already
        enqueued — including a farewell — then exits on the sentinel)
        before the caller closes the socket. A direct farewell would
        OVERTAKE queued stream events (the client stops at bye/detached,
        losing its FinalTurnComplete). The default budget is
        FINISH_TIMEOUT: interactive paths that bypass _drain_conns
        (the 'q' detach ack) must not stall half a minute behind one
        wedged writer."""
        self.request_finish()
        self.join_writer(self.FINISH_TIMEOUT if timeout is None else timeout)

    def close(self) -> None:
        self._dead.set()
        if self._handle is not None:
            self._handle.kill()
        with contextlib.suppress(queue.Full):
            self._out.put_nowait(None)  # release the legacy writer
        with contextlib.suppress(OSError):
            self.sock.shutdown(socket.SHUT_RDWR)
        with contextlib.suppress(OSError):
            self.sock.close()


def publish_listen_addr(address) -> None:
    """One info-style gauge naming this process's serving address —
    how `obs.console` joins a relay's `upstream` label to the endpoint
    actually scraped, so the fan-out tree renders from metrics alone."""
    obs.gauge(
        "gol_tpu_server_listen_addr",
        "Serving address of this process (info gauge, value 1)",
        {"addr": f"{address[0]}:{address[1]}"},
    ).set(1)


def _clamp_batch(hello: dict, cap: int) -> int:
    """The peer's hello "batch" max-k request, clamped to the server's
    --batch-turns ceiling AND the wire frame's own hard turn cap —
    an operator cap above FBATCH_MAX_TURNS must never let the server
    negotiate frames its peer's parser is required to reject.
    Hostile/non-integer values read as 0 (no batching) — the request
    is an optimization, never an error."""
    if cap <= 0:
        return 0
    req = hello.get("batch")
    if isinstance(req, bool) or not isinstance(req, int):
        return 0
    return max(0, min(req, cap, wire.FBATCH_MAX_TURNS))


def _encode_and_send_flips(conn: _Conn, turn: int, flips, flips_levels,
                           width: int, height: int,
                           delta_words=None) -> None:
    """One turn's flips in `conn`'s negotiated encoding — the single
    encode both the singleton broadcaster and the per-session sinks
    (SessionServer) share, so the session layer feeds the PR 4 wire
    encodings unchanged. `delta_words` is a pre-built (bitmap, words)
    pair when the caller amortized the encode across delta peers."""
    lv = flips_levels if conn.levels else None
    if conn.delta and lv is None:
        # Delta-of-sparse (r6): changed-word masks with the bitmap
        # delta'd against this peer's previous sent turn — on a
        # settled board the recurring active words XOR to near
        # nothing and zlib collapses the bitmap term. Level batches
        # keep the LFLIPS frame (levels are not XOR state).
        bitmap, words = (delta_words if delta_words is not None
                         else wire.coords_to_words(flips, width, height))
        prev = conn.delta_prev
        conn.delta_prev = bitmap
        conn.send_raw(wire.delta_flips_to_frame(
            turn, bitmap if prev is None else bitmap ^ prev, words
        ))
    elif conn.binary:
        conn.send_raw(
            wire.level_flips_to_frame(turn, flips, lv)
            if lv is not None
            else wire.flips_to_frame(turn, flips)
        )
    elif conn.compact:
        conn.send(wire.flips_to_msg(turn, flips, levels=lv))
    else:
        # Legacy JSON peers are two-state; levels are dropped
        # (they could not apply them anyway).
        conn.send({"t": "flips", "turn": turn,
                   "cells": np.asarray(flips).tolist()})


class EngineServer:
    """Serve one engine run to at-most-one controller at a time."""

    def __init__(
        self,
        params: Params,
        host: str = "127.0.0.1",
        port: int = 8030,
        *,
        resume_from: Optional[str] = None,
        secret: Optional[str] = None,
        heartbeat_secs: float = 2.0,
        evict_secs: Optional[float] = None,
        max_peers: Optional[int] = None,
        high_water: Optional[int] = None,
        drain_secs: Optional[float] = None,
        retry_after_secs: float = 1.0,
        batch_turns: int = 1024,
        writer_pool_threads: int = 2,
        **engine_kwargs,
    ):
        self.params = params
        #: Selector-based writer event loop (gol_tpu.relay.writerpool):
        #: every attached peer's outbound frames ride one of these few
        #: threads — thousands of sockets per thread instead of one
        #: writer thread per connection (ROADMAP item 1's event-loop
        #: half). 0 restores the legacy thread-per-connection writers.
        self.pool = (WriterPool(writer_pool_threads, "gol-srv-writer")
                     if writer_pool_threads > 0 else None)
        #: Server-side ceiling on a peer's hello "batch" request (the
        #: max turns one flip-batch frame may carry; CLI
        #: --batch-turns). 0 disables batch negotiation entirely —
        #: every peer gets per-turn frames.
        self.batch_turns = max(0, batch_turns)
        #: Admission budget (docs/RESILIENCE.md "Overload &
        #: degradation"): attaches past this many live peers are
        #: rejected "at-capacity" WITH a retry_after hint, instead of
        #: accepted into a serving plane that can no longer keep up.
        #: None = unbounded (legacy).
        self.max_peers = max_peers
        self.high_water = high_water
        self.drain_secs = drain_secs
        #: The hint every load rejection ("busy", "at-capacity")
        #: carries: seconds the peer should wait before re-dialing —
        #: the PR 3 client backoff honors it instead of guessing.
        self.retry_after_secs = max(0.0, retry_after_secs)
        #: Liveness cadence (docs/RESILIENCE.md): beacons ride idle
        #: gaps in each peer's stream every `heartbeat_secs`; an
        #: hb-capable peer silent past `evict_secs` (default 3 beacon
        #: intervals) with unanswered beacons outstanding is evicted.
        #: 0 disables the whole plane (legacy behavior).
        self.heartbeat_secs = max(0.0, heartbeat_secs)
        self.evict_secs = (
            evict_secs if evict_secs is not None
            else 3.0 * self.heartbeat_secs
        )
        #: Shared-secret attach token. When set, a hello whose "secret"
        #: does not match is rejected and logged — the board state and
        #: the 'k' kill verb are not for any peer that can reach the
        #: port (the reference's open :8030 listener,
        #: ref: gol/distributor.go:49-52, is a flaw to beat, not match).
        self._secret = secret
        if resume_from is not None:
            engine_kwargs.setdefault("initial_world", read_pgm(resume_from))
            engine_kwargs.setdefault("start_turn", snapshot_turn(resume_from))
        # Crash-restart visibility: the turn this process booted from
        # (0 on a fresh start) — the smoke harness and operators read
        # it to confirm a --resume actually resumed.
        from gol_tpu.checkpoint import record_resume_turn

        record_resume_turn(engine_kwargs.get("start_turn", 0))
        self._keys: queue.Queue = queue.Queue()
        # Flips ride as per-turn FlipBatch arrays: the broadcaster and
        # the wire consume them vectorized — per-cell Python event
        # objects capped the whole watched pipeline at ~30 turns/s.
        self.engine = Engine(
            params, keypresses=self._keys, emit_flips=False,
            emit_flip_batches=True, **engine_kwargs
        )
        self._listener = socket.create_server((host, port))
        self.address = self._listener.getsockname()
        publish_listen_addr(self.address)
        #: Freshness plane (docs/OBSERVABILITY.md "Freshness plane"):
        #: per-peer turn age vs the engine's committed turn, sampled
        #: by the broadcaster's per-turn housekeeping and the
        #: heartbeat sweep (rate-limited inside).
        self.freshness = ServerFreshness("engine")
        self._conn: Optional[_Conn] = None
        #: Read-only observers fanned out from the same event stream —
        #: the controller ⇄ broker ⇄ workers topology's natural "one
        #: driver plus N watchers" shape (ref: README.md:201-207 keeps
        #: the DRIVER singular; nothing about watching is exclusive).
        self._observers: "list[_Conn]" = []
        self._conn_lock = lockcheck.make_lock("EngineServer._conn_lock")
        self._shutdown = threading.Event()
        self.done = threading.Event()
        self._threads: list[threading.Thread] = []

    # --- lifecycle ---

    def start(self) -> "EngineServer":
        self.engine.start()
        loops = [(self._accept_loop, "gol-accept"),
                 (self._broadcast_loop, "gol-broadcast")]
        if self.heartbeat_secs > 0:
            loops.append((self._heartbeat_loop, "gol-heartbeat"))
        for fn, name in loops:
            t = threading.Thread(target=fn, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def shutdown(self, *, stop_engine: bool = True) -> None:
        if self._shutdown.is_set():
            return
        self._shutdown.set()
        if stop_engine:
            self.engine.stop()
        with contextlib.suppress(OSError):
            # SHUT_RDWR first: on Linux, close() alone does NOT wake a
            # thread parked in accept() — the zombie accept holds the
            # LISTEN socket alive and the port stays bound, so an
            # in-process restart on the same address gets EADDRINUSE.
            self._listener.shutdown(socket.SHUT_RDWR)
        with contextlib.suppress(OSError):
            self._listener.close()
        self._drain_conns()
        self.engine.join(timeout=60)
        if self.pool is not None:
            self.pool.close()
        # A dead server's last worst-age reading must not stay glued
        # to the registry (fleet AGE columns and max() alert rules
        # read the family).
        self.freshness.close()
        self.done.set()

    #: Per-peer writer-drain budget at teardown. Writers drain
    #: CONCURRENTLY (every sentinel is enqueued before any join), so
    #: run-end with a driver plus several wedged observers costs at
    #: most ~this once, not 30s per stuck peer (ADVICE r5 #3).
    DRAIN_TIMEOUT = 5.0

    def _drain_conns(self) -> None:
        """Collect-and-clear every attached connection under the lock,
        then farewell + close each — the one teardown used by
        shutdown() and the broadcast epilogue. Phase 1 enqueues every
        peer's farewell and exit sentinel (non-blocking); phase 2 joins
        the writers, which have all been draining in parallel since
        phase 1, with a short per-peer timeout."""
        with self._conn_lock:
            conns = list(self._observers)
            if self._conn is not None:
                conns.append(self._conn)
            self._conn = None
            self._observers = []
        for conn in conns:
            with contextlib.suppress(Exception):
                conn.send({"t": "bye"})
            conn.request_finish()
        deadline = time.monotonic() + self.DRAIN_TIMEOUT
        for conn in conns:
            conn.join_writer(max(0.1, deadline - time.monotonic()))
            conn.close()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self.done.wait(timeout)

    def health(self) -> dict:
        """Liveness snapshot for /healthz: the engine's health plus the
        serving plane (host-side state only — probe-hammer safe)."""
        info = self.engine.health()
        with self._conn_lock:
            info["peers"] = len(self._observers) + (
                1 if self._conn is not None else 0
            )
            info["driver_attached"] = self._conn is not None
        info["address"] = list(self.address)
        if self._shutdown.is_set() and info["status"] == "ok":
            info["status"] = "shutting-down"
        return info

    # --- accept path ---

    #: A connected peer gets this long to produce its hello. Without a
    #: deadline, one silent TCP connect wedges the (single) accept
    #: thread forever — no further peer could ever attach.
    HELLO_TIMEOUT = 10.0

    def _accept_loop(self) -> None:
        from gol_tpu.testing import faults

        while not self._shutdown.is_set():
            try:
                sock, addr = self._listener.accept()
            except OSError:
                return  # listener closed
            # Deterministic fault injection (GOL_TPU_FAULTS) — a
            # passthrough unless a plan names the server role.
            sock = faults.wrap("server", sock)
            _METRICS.accepts.inc()
            try:
                sock.settimeout(self.HELLO_TIMEOUT)
                # Control-only receive: an unauthenticated peer must
                # never make the server inflate a bulk zlib payload.
                hello = wire.recv_msg(sock, allow_binary=False)
                if not hello or hello.get("t") != "hello":
                    raise wire.WireError(f"bad hello: {hello!r}")
            except (wire.WireError, OSError, ValueError) as e:
                log.warning("rejecting connection from %s: %s", addr, e)
                _METRICS.rejects["bad-hello"].inc()
                sock.close()
                continue

            # Compare as UTF-8 bytes: compare_digest on str raises
            # TypeError for non-ASCII input, and the secret here is
            # attacker-controlled — a unicode probe must be a clean
            # rejection, not a dead accept thread.
            if self._secret is not None and not hmac.compare_digest(
                str(hello.get("secret", "")).encode("utf-8", "replace"),
                self._secret.encode("utf-8", "replace"),
            ):
                log.warning(
                    "rejecting unauthenticated attach from %s", addr
                )
                _METRICS.rejects["unauthorized"].inc()
                with contextlib.suppress(Exception):
                    wire.send_msg(
                        sock, {"t": "error", "reason": "unauthorized"}
                    )
                sock.close()
                continue

            if (self.max_peers is not None
                    and self._peer_count() >= self.max_peers):
                # Admission control: a full house sheds the attach at
                # the door, WITH a when-to-come-back hint — an
                # unbounded observer pile-up is how the serving plane
                # stops keeping up for everyone already attached.
                _METRICS.rejects["at-capacity"].inc()
                with contextlib.suppress(Exception):
                    wire.send_msg(sock, {
                        "t": "error", "reason": "at-capacity",
                        "retry_after": self.retry_after_secs,
                    })
                sock.close()
                continue
            role = ("observe" if hello.get("role") == "observe"
                    else "drive")
            # Heartbeat negotiation: the peer advertises support, we
            # confirm the cadence in the attach-ack; only hb peers are
            # ever evicted for silence.
            hb = bool(hello.get("hb", False)) and self.heartbeat_secs > 0
            conn = _Conn(sock, bool(hello.get("want_flips", False)),
                         compact=bool(hello.get("compact", False)),
                         binary=bool(hello.get("binary", False)),
                         levels=bool(hello.get("levels", False)),
                         role=role, hb=hb,
                         delta=bool(hello.get("delta", False)),
                         batch=_clamp_batch(hello, self.batch_turns),
                         high_water=self.high_water,
                         drain_secs=self.drain_secs,
                         pool=self.pool)
            if role == "observe":
                # Observers fan out freely — only the DRIVER slot is
                # exclusive (its verbs steer the run).
                with self._conn_lock:
                    self._observers.append(conn)
                busy = False
            else:
                with self._conn_lock:
                    if self._conn is not None:
                        busy = True
                    else:
                        self._conn, busy = conn, False
            if busy:
                # One DRIVER at a time (the reference's controller is
                # singular too, ref: README.md:201-207). The hint lets
                # a waiting driver back off for exactly as long as the
                # server believes the slot needs, not a blind guess.
                _METRICS.rejects["busy"].inc()
                with contextlib.suppress(Exception):
                    wire.send_msg(sock, {
                        "t": "error", "reason": "busy",
                        "retry_after": self.retry_after_secs,
                    })
                sock.close()
                continue
            _METRICS.attaches[role].inc()
            _METRICS.peers.set(self._peer_count())
            install_lag_gauge(conn)

            # Immediate ack: the controller's handshake timeout covers
            # the first reply, and the BoardSync only arrives once the
            # engine services the attach between dispatches — on a cold
            # TPU that can be a 40s compile away. The ack lands within
            # ms so attaches never time out behind a dispatch (clients
            # ignore unknown message kinds, so old ones are unaffected).
            # Clock-probe negotiation (docs/OBSERVABILITY.md): the ack
            # advertises that this server echoes {"t":"clk"} probes
            # with its wall clock, so the peer can estimate the
            # emit-stamp offset instead of documenting the skew. Legacy
            # peers ignore the unknown key.
            ack = {"t": "attach-ack", "clock": True, "depth": 0}
            if conn.batch:
                # Confirm the clamped max-k, so the peer knows the
                # granularity its frames will arrive at.
                ack["batch"] = conn.batch
            if hb:
                # The client arms its own miss-detector from this: a
                # server that stays silent past a few multiples of
                # hb_secs is dead, and reconnecting is correct.
                ack["hb_secs"] = self.heartbeat_secs
            try:
                conn.send(ack)
            except (wire.WireError, OSError):
                self._detach(conn)
                continue
            try:
                conn.start_writer(self._detach)
            except wire.WireError:
                self._detach(conn)
                continue
            tracing.event("server.attach", "lifecycle", role=role,
                          token=conn.token)
            flight.note("server.attach", role=role, token=conn.token)
            self._attach(conn)
            threading.Thread(
                target=self._reader_loop, args=(conn,),
                name="gol-conn-reader", daemon=True,
            ).start()

    def _attach(self, conn: _Conn) -> None:
        """Ask the engine to publish a BoardSync (and, if wanted, start
        per-turn flips) at its next dispatch boundary. Both ride the
        event stream, so the broadcaster delivers them in turn order —
        no side-channel race between the sync and newer diffs.

        Per-turn TurnComplete events flow whenever ANY controller is
        attached (flips or not — a headless controller still follows
        progress, ref: sdl/loop.go:44-47 prints per-event); a detached
        engine emits none and runs full-size fused chunks."""
        self.engine.emit_turns = True
        if conn.batch:
            # A batching watcher: diff chunks emit as whole FlipChunk
            # events, and the dispatch chunk budget scales to the
            # negotiated max-k (ISSUE 10's chunk-pinning fix).
            self.engine.emit_flip_chunks = True
            self.engine.batch_turns_hint = max(
                self.engine.batch_turns_hint, conn.batch
            )
        self.engine.request_board_sync(
            enable_flips=conn.want_flips, token=conn.token
        )

    def _peer_count(self) -> int:
        with self._conn_lock:
            return len(self._observers) + (1 if self._conn is not None else 0)

    def _release(self, conn: _Conn) -> None:
        """Free the connection's slot (driver or observer) without
        closing the socket, re-deriving the engine flags from whoever
        remains attached."""
        removed = False
        with self._conn_lock:
            if self._conn is conn:
                self._conn = None
                removed = True
            elif conn in self._observers:
                self._observers.remove(conn)
                removed = True
            self._set_flags_locked()
            remaining = len(self._observers) + (
                1 if self._conn is not None else 0
            )
        if removed:  # idempotent under the detach/close double-call
            _METRICS.detaches.inc()
            remove_lag_gauge(conn)
            self.freshness.forget(conn.token)
            _forget_peer_usage(conn)
            tracing.event("server.detach", "lifecycle", role=conn.role,
                          token=conn.token)
            flight.note("server.detach", role=conn.role, token=conn.token)
        _METRICS.peers.set(remaining)

    def _detach(self, conn: _Conn) -> None:
        self._release(conn)
        conn.close()

    def _set_flags_locked(self) -> None:
        """Engine flag refresh — call with _conn_lock held: per-turn
        events flow while ANY connection is attached, flips while any
        attached connection wants them."""
        conns = list(self._observers)
        if self._conn is not None:
            conns.append(self._conn)
        self.engine.emit_flips = any(c.want_flips for c in conns)
        self.engine.emit_turns = bool(conns)
        self.engine.emit_flip_chunks = any(c.batch for c in conns)
        self.engine.batch_turns_hint = max(
            (c.batch for c in conns), default=0
        )

    def _all_conns(self) -> "list[_Conn]":
        with self._conn_lock:
            conns = list(self._observers)
            if self._conn is not None:
                conns.append(self._conn)
        return conns

    def _refresh_flips(self) -> None:
        """Re-derive engine.emit_flips/emit_turns from the currently
        attached connections, atomically against attach/detach — the
        single writer discipline that keeps broadcaster-side corrections
        from racing a concurrent _detach or a fresh attach."""
        with self._conn_lock:
            self._set_flags_locked()

    # --- controller → engine ---

    def _reader_loop(self, conn: _Conn) -> None:
        while True:
            try:
                # Controllers only ever send JSON control messages.
                msg = wire.recv_msg(conn.sock, allow_binary=False)
            except TimeoutError:
                # Idle expiry at a frame boundary (wire.recv_msg): not
                # a failure — the heartbeat thread owns the eviction
                # verdict; this loop just wakes at the deadline cadence
                # instead of blocking unboundedly.
                if conn._dead.is_set():
                    self._detach(conn)
                    return
                continue
            except (wire.WireError, OSError):
                msg = None
            if msg is None:  # controller went away (crash or close)
                self._detach(conn)
                return
            # ANY inbound byte proves the peer alive — heartbeat pongs
            # exist precisely to generate this refresh on idle links.
            conn.last_rx = time.monotonic()
            conn.hb_unanswered = 0
            if msg.get("t") == "clk":
                # Clock probe: echo the peer's t0 with our wall clock,
                # immediately and queue-free (send_direct) — the reply
                # delay IS the measurement error. The probe is
                # observer-safe: it steers nothing.
                with contextlib.suppress(wire.WireError, OSError):
                    conn.send_direct({"t": "clk", "t0": msg.get("t0"),
                                      "ts": time.time()})
                continue
            if msg.get("t") != "key":
                continue
            key = msg.get("key")
            if conn.role == "observe" and key != "q":
                # Observers are read-only: steering verbs are rejected
                # (the driver slot exists precisely to arbitrate them);
                # 'q' below only detaches the observer itself.
                with contextlib.suppress(Exception):
                    conn.send({"t": "error", "reason": "observer"})
                continue
            if key in ("p", "s"):
                self._keys.put(key)
            elif key == "q":
                # Detach only — the engine keeps evolving
                # (ref: README.md:182). The slot is freed BEFORE the
                # ack: a controller that reattaches the moment
                # `detach()` returns must never bounce off its own
                # stale registration ("busy" race, seen under load).
                self._release(conn)
                with contextlib.suppress(Exception):
                    conn.send({"t": "detached"})
                conn.finish()
                conn.close()
                return
            elif key == "k":
                # Global shutdown with a final snapshot (ref: README.md:183).
                self._keys.put("k")
                return  # broadcaster sends the tail + bye, then shutdown

    # --- liveness (docs/RESILIENCE.md) ---

    #: Beacons that must go unanswered (on top of the evict_secs
    #: silence) before a peer is evicted — eviction requires PROBED
    #: silence, so a peer that is merely quiet behind a busy outbound
    #: stream (no idle gap → no beacons sent) is never judged by a
    #: clock nothing refreshed.
    HB_MISS_LIMIT = 3

    def _heartbeat_loop(self) -> None:
        interval = max(0.05, self.heartbeat_secs / 2.0)
        while not self._shutdown.wait(interval):
            now = time.monotonic()
            turn = self.engine.completed_turns
            conns = self._all_conns()
            # Freshness sweep off the liveness cadence: a degraded or
            # idle peer's turn age keeps moving even when the
            # broadcaster has nothing to fan out.
            self.freshness.sample((c, None) for c in conns)
            # Accounting sweep on the same cadence: a peer's writer
            # backlog occupies event-queue memory whether or not the
            # broadcaster is emitting — queued frames × sweep interval
            # is the frame-seconds each principal held.
            _meter = accounting.meter()
            if _meter is not None:
                for c in conns:
                    q = c.queued()
                    if q:
                        _meter.charge(c.principal,
                                      queue_frame_seconds=q * interval)
            for conn in conns:
                if not conn.writer_started:
                    # Mid-handshake: the attach-ack (which carries the
                    # hb cadence and must be the peer's FIRST message)
                    # is sent before start_writer — never overtake it.
                    continue
                if conn.degraded:
                    # The degradation plane owns a degraded peer's
                    # verdict: no beacons into a backlogged queue, and
                    # no hb-eviction racing the drain deadline (a
                    # stalled reader can't answer beacons precisely
                    # while it is the peer degradation exists to keep
                    # alive). Drained → coalescing resync (also checked
                    # per turn by the broadcaster; this covers paused/
                    # idle engines); wedged past drain_secs → the one
                    # overflow-eviction left.
                    if conn.drained():
                        conn.resync_pending = True
                        self.engine.request_board_sync(
                            enable_flips=conn.want_flips,
                            token=conn.token,
                        )
                    elif (now - conn.degraded_since > conn.drain_secs
                          and conn.queued() > conn.LOW_WATER):
                        log.warning(
                            "evicting peer %d: wedged %.1fs past the "
                            "drain deadline (%d frames queued)",
                            conn.token, now - conn.degraded_since,
                            conn.queued(),
                        )
                        if conn.count_overflow():
                            _METRICS.overflows.inc()
                            flight.note("server.drain_evict",
                                        token=conn.token)
                        self._detach(conn)
                    continue
                if (conn.hb and conn.hb_unanswered >= self.HB_MISS_LIMIT
                        and now - conn.last_rx > self.evict_secs):
                    log.warning(
                        "evicting unresponsive peer (silent %.1fs, %d "
                        "beacons unanswered)", now - conn.last_rx,
                        conn.hb_unanswered,
                    )
                    _METRICS.evicted.inc()
                    tracing.event("server.evict", "lifecycle",
                                  role=conn.role, token=conn.token,
                                  silent_s=round(now - conn.last_rx, 3))
                    flight.note("server.evict", role=conn.role,
                                token=conn.token,
                                silent_s=round(now - conn.last_rx, 3))
                    self._detach(conn)
                    # An eviction is the black-box moment for the peer
                    # that just vanished: snapshot the recent history
                    # (crash-atomic, no-op without a configured dir) so
                    # the post-mortem exists even if whatever killed
                    # the peer takes this process down next.
                    flight.dump("peer-eviction")
                    # An eviction is instability evidence: nudge an
                    # immediate checkpoint (engine 's' verb, async +
                    # crash-atomic) so a restart after whatever killed
                    # the peer loses at most the heartbeat deadline,
                    # not a full autosave interval.
                    if (self.params.autosave_turns > 0
                            or self.params.autosave_seconds > 0):
                        self._keys.put("s")
                    continue
                if now - conn.last_tx >= self.heartbeat_secs:
                    try:
                        if conn.binary:
                            conn.send_raw(wire.heartbeat_to_frame(turn))
                        else:
                            conn.send({"t": "hb", "turn": turn})
                    except (wire.WireError, OSError):
                        self._detach(conn)
                        continue
                    _METRICS.heartbeats.inc()
                    if conn.hb:
                        conn.hb_unanswered += 1

    # --- engine → controller ---

    def _delta_words(self, flips):
        """The peer-INDEPENDENT half of the delta-of-sparse encode —
        one (bitmap, words) build per flushed turn, shared by every
        delta peer (only the XOR against each peer's chain state and
        the zlib are per-connection; re-encoding per observer would be
        redundant hot-path CPU in the single broadcaster thread)."""
        return wire.coords_to_words(
            flips, self.params.image_width, self.params.image_height
        )

    def _send_flips(self, conn: _Conn, turn: int, flips,
                    flips_levels, delta_words=None) -> None:
        """One turn's batched flips in this connection's negotiated
        encoding (binary frame / compact JSON / legacy pairs; levels
        ride only to peers that advertised the capability).
        `delta_words` is the shared per-turn (bitmap, words) pair for
        delta peers (see _delta_words)."""
        m = accounting.meter()
        t0 = time.perf_counter() if m is not None else 0.0
        with tracing.span("wire.encode_flips", "wire", turn=turn):
            _encode_and_send_flips(
                conn, turn, flips, flips_levels,
                self.params.image_width, self.params.image_height,
                delta_words,
            )
        if m is not None:
            # Host encode tax at the PR 5 span boundary — attributed
            # to the peer whose negotiated encoding we just paid for.
            m.charge(conn.principal,
                     host_seconds=time.perf_counter() - t0)

    def _send_stream_event(self, conn: _Conn, ev) -> None:
        """One post-sync event in this connection's encoding.

        TurnComplete messages carry a `ts` wall-clock stamp taken at
        enqueue: the client measures emit→apply lag against it — the
        first END-TO-END (cross-process) latency signal the system has
        (gol_tpu_client_turn_latency_seconds). Peers that predate the
        field ignore it (unknown JSON keys pass through); clocks are
        shared on a same-host pair and NTP-close across hosts — skew
        bounds are documented in docs/OBSERVABILITY.md."""
        if conn.binary and isinstance(ev, FinalTurnComplete):
            conn.send_raw(wire.final_to_frame(ev.completed_turns, ev.alive))
        else:
            msg = wire.event_to_msg(ev)
            if isinstance(ev, TurnComplete):
                msg["ts"] = time.time()
            conn.send(msg)

    def _broadcast_chunk(self, ev: FlipChunk, conns) -> None:
        """Fan one k-turn FlipChunk out: batch peers get ONE encoded
        frame (shared per distinct negotiated max-k — encode runs
        once, before any per-peer state moves), per-turn peers get the
        expanded flips/TurnComplete stream they always got (expansion
        also computed at most once per chunk). The per-turn
        housekeeping the TurnComplete branch used to do — lag gauges,
        drain-resync checks, the wire-correlation mark — runs per
        chunk here; shedding (offer_stream) gates whole batches."""
        k = len(ev.counts)
        last = ev.completed_turns
        _METRICS.chunks.inc()
        self.freshness.note_commit(last)
        depth = 0
        for c in conns:
            q = c.queued()
            depth = max(depth, q)
            if c.lag_metric is not None:
                c.lag_metric.set(q)
            if c.drained():
                c.resync_pending = True
                self.engine.request_board_sync(
                    enable_flips=c.want_flips, token=c.token
                )
        _METRICS.queue_depth.set(depth)
        self.freshness.sample((c, None) for c in conns)
        tracing.event("turn.emit", "wire", turn=last, batch=k)
        ts = time.time()
        enc: dict = {}
        expanded = None
        for conn in conns:
            if not conn.synced or last <= conn.synced_turn:
                continue
            try:
                if not conn.offer_stream():
                    continue
                if conn.batch and conn.want_flips:
                    frames = enc.get(conn.batch)
                    if frames is None:
                        with tracing.span("wire.encode_batch", "wire",
                                          turn=last, turns=k):
                            frames = encode_batch_frames(
                                ev.counts, ev.bitmaps, ev.words,
                                ev.first_turn, self.params.image_width,
                                self.params.image_height, conn.batch,
                                ts,
                            )
                        enc[conn.batch] = frames
                    for f in frames:
                        conn.send_raw(f)
                else:
                    if expanded is None:
                        expanded = self._expand_chunk(ev)
                    self._send_chunk_expanded(conn, ev, expanded, ts)
                conn.note_written(last)
            except (wire.WireError, OSError):
                self._detach(conn)

    def _expand_chunk(self, ev: FlipChunk):
        """Per-turn (coords, bitmap, words) triples of one chunk, for
        peers still on per-turn frames — None entries for flip-less
        turns. Built once per chunk, shared across such peers."""
        W, H = self.params.image_width, self.params.image_height
        counts = np.asarray(ev.counts, np.int64)
        offs = np.zeros(len(counts) + 1, np.int64)
        np.cumsum(counts, out=offs[1:])
        out = []
        for t in range(len(counts)):
            if not counts[t]:
                out.append(None)
                continue
            words = ev.words[offs[t]:offs[t + 1]]
            bm = np.asarray(ev.bitmaps[t], np.uint32)
            out.append((wire.words_to_coords(bm, words, W, H), bm, words))
        return out

    def _send_chunk_expanded(self, conn: _Conn, ev: FlipChunk,
                             expanded, ts: float) -> None:
        """One chunk to one per-turn peer: exactly the flips-then-
        TurnComplete stream the per-turn emit path produced, turn by
        turn (synced_turn still gates per turn — a chunk may straddle
        this peer's sync)."""
        W, H = self.params.image_width, self.params.image_height
        for t, entry in enumerate(expanded):
            turn = ev.first_turn + t
            if turn <= conn.synced_turn:
                continue
            if entry is not None and conn.want_flips:
                coords, bm, words = entry
                with tracing.span("wire.encode_flips", "wire",
                                  turn=turn):
                    _encode_and_send_flips(conn, turn, coords, None,
                                           W, H, (bm, words))
            conn.send({"t": "ev", "k": "turn", "turn": turn, "ts": ts})

    def _broadcast_loop(self) -> None:
        """Single consumer of the engine's event stream, fanning out to
        the driver and every observer (r5 multi-observer serving); each
        turn's flips become one wire message per interested connection
        — from a FlipBatch array directly (the engine's vectorized
        form) or by batching a CellFlipped burst (engines injected with
        the per-cell contract)."""
        # Opt-in stream monitor (gol_tpu.analysis.invariants): asserts
        # the orderings this loop RELIES on — FlipBatch/TurnComplete
        # adjacency, no flips straddling a BoardSync, monotone turns —
        # so an engine emission change breaks a test instead of
        # XOR-corrupting an attached peer.
        from gol_tpu.analysis.invariants import (
            EventStreamChecker,
            invariants_enabled,
        )

        checker = (EventStreamChecker("server-broadcast")
                   if invariants_enabled() else None)
        try:
            self._broadcast_events(checker)
        except Exception:
            # A violated invariant (or any broadcaster bug) must not
            # leave a zombie server: full teardown, then let the
            # exception surface in the thread log.
            self.shutdown()
            raise
        # Engine stream closed: the run is over (final turn, 'k', or stop).
        self._drain_conns()
        self.shutdown(stop_engine=False)

    def _broadcast_events(self, checker) -> None:
        flips: "list | object" = []
        flips_levels = None  # (N,) gray levels of a multi-state batch
        flips_turn = 0
        for ev in self.engine.events:
            if checker is not None:
                checker.observe(ev)
            _METRICS.events.inc()
            conns = self._all_conns()
            if isinstance(ev, FlipBatch):
                if len(ev.cells) and any(c.want_flips for c in conns):
                    flips_turn = ev.completed_turns
                    flips = ev.cells
                    flips_levels = getattr(ev, "levels", None)
                continue
            if isinstance(ev, CellFlipped):
                if any(c.want_flips for c in conns):
                    flips_turn = ev.completed_turns
                    if not isinstance(flips, list):
                        # Mixed batch/per-cell stream: the stale batch
                        # AND its levels both reset (a leftover levels
                        # array would fail the flush's length check).
                        flips = []
                        flips_levels = None
                    flips.append([ev.cell.x, ev.cell.y])
                continue
            if isinstance(ev, FlipChunk):
                # The chunk-granular stream (batching watchers
                # attached): k turns in one event — ONE wire frame per
                # batch peer, per-turn expansion only for peers that
                # still consume per-turn frames.
                if conns:
                    self._broadcast_chunk(ev, conns)
                continue
            if not conns:
                flips = []
                flips_levels = None
                if isinstance(ev, BoardSync):
                    # Sync requested by a connection that vanished: drop
                    # the stale enable_flips so a watcher-less engine
                    # pays zero diff tax (re-derived under the lock — a
                    # new connection may have just attached).
                    self._refresh_flips()
                continue
            if isinstance(ev, BoardSync):
                target = next(
                    (c for c in conns if c.token == ev.token), None
                )
                if target is None:
                    # Sync for a connection that vanished before it was
                    # serviced; re-derive the subscription from the
                    # CURRENT connections (by want_flips alone — their
                    # own syncs may still be queued behind this one).
                    self._refresh_flips()
                    continue
                try:
                    if target.binary:
                        target.send_raw(wire.board_to_frame(
                            ev.completed_turns, ev.world, ev.token
                        ))
                    else:
                        target.send(wire.board_to_msg(
                            ev.completed_turns, ev.world, ev.token
                        ))
                    target.synced = True
                    # The synced board already contains every flip up
                    # to its turn: record it so a flush of flips
                    # buffered BEFORE this sync skips this peer (other
                    # peers are still owed them). Today the engine
                    # never emits a BoardSync between a FlipBatch and
                    # its TurnComplete — the checker above asserts that
                    # — but the broadcaster no longer depends on it.
                    target.synced_turn = ev.completed_turns
                    # A synced raster is the freshest possible write:
                    # everything up to its turn is inside it.
                    target.note_written(ev.completed_turns)
                    # The synced raster restarts the delta-of-sparse
                    # chain: the client resets its own prev bitmap on
                    # the board message, so the next flips frame must
                    # carry the full bitmap again.
                    target.delta_prev = None
                    # If this sync was the degradation plane's
                    # coalescing resync, the peer's stream is whole
                    # again: everything it shed is inside this raster.
                    target.mark_recovered()
                except (wire.WireError, OSError):
                    self._detach(target)
                continue
            flush = len(flips) and isinstance(ev, TurnComplete)
            if isinstance(ev, TurnComplete):
                # Backpressure visibility: per-peer lag gauges plus the
                # deepest writer queue (one qsize sweep per turn, not
                # per frame — a lagging peer shows up here long before
                # any eviction), and the drain check that turns a
                # recovered slow consumer's backlog into ONE coalesced
                # BoardSync at the engine's next dispatch boundary.
                depth = 0
                for c in conns:
                    q = c.queued()
                    depth = max(depth, q)
                    if c.lag_metric is not None:
                        c.lag_metric.set(q)
                    if c.drained():
                        c.resync_pending = True
                        self.engine.request_board_sync(
                            enable_flips=c.want_flips, token=c.token
                        )
                _METRICS.queue_depth.set(depth)
                self.freshness.note_commit(ev.completed_turns)
                self.freshness.sample((c, None) for c in conns)
                # The SERVER half of the per-turn wire correlation: one
                # instant mark per broadcast turn, carrying the turn
                # number — `report merge` pairs it with the client's
                # `turn.apply` on the offset-corrected timebase.
                tracing.event("turn.emit", "wire",
                              turn=ev.completed_turns)
            delta_words = None
            if flush and flips_levels is None and any(
                    c.delta and c.synced and c.want_flips
                    and flips_turn > c.synced_turn for c in conns):
                # One shared encode per flushed turn for every delta
                # peer (the XOR/zlib stay per-connection).
                delta_words = self._delta_words(flips)
            for conn in conns:
                if not conn.synced:
                    continue  # pre-sync events are not this peer's
                try:
                    # The per-turn stream plane is SHEDDABLE: a peer
                    # past its high-water mark silently misses flips
                    # and turn events here and is made whole by the
                    # coalescing BoardSync once its queue drains.
                    # FinalTurnComplete is the run's result — once per
                    # run, control-plane, never shed. The gate runs
                    # BEFORE any encode, so a shed frame never
                    # advances this peer's delta chain.
                    if not isinstance(ev, FinalTurnComplete) \
                            and not conn.offer_stream():
                        continue
                    if flush and conn.want_flips \
                            and flips_turn > conn.synced_turn:
                        self._send_flips(conn, flips_turn, flips,
                                         flips_levels, delta_words)
                    self._send_stream_event(conn, ev)
                    if isinstance(ev, (TurnComplete, FinalTurnComplete)):
                        conn.note_written(ev.completed_turns)
                except (wire.WireError, OSError):
                    self._detach(conn)
            if flush:
                flips = []
                flips_levels = None


def encode_batch_frames(counts, bitmaps, words, first_turn: int,
                        width: int, height: int, bsize: int,
                        ts: float) -> "list[bytes]":
    """One chunk's _TAG_FBATCH frames for a peer whose negotiated
    max-k is `bsize`: the chunk splits into ceil(k/bsize) independent
    frames (each self-contained — `wire.chunk_deltas` re-bases the
    turn-axis delta at every segment start). Shared by the singleton
    broadcaster and the per-session sinks; observes the per-frame
    batch-size histogram."""
    total, nb = wire.grid_words(width, height)
    _METRICS.chunk_encodes.inc()
    k = len(counts)
    frames = []
    for a in range(0, k, bsize):
        b = min(a + bsize, k)
        dc, dbm, dw = wire.chunk_deltas(counts, bitmaps, words,
                                        a, b, total)
        frames.append(wire.flip_batch_to_frame(
            first_turn + a, nb, dc, dbm, dw, ts
        ))
        _METRICS.batch_turns.observe(b - a)
    return frames


class _SessionSink:
    """gol_tpu.sessions.Sink feeding one attached connection: board
    syncs, per-turn flips in the connection's negotiated encoding, and
    ts-stamped TurnComplete messages — the per-session twin of the
    singleton broadcaster. Callbacks run on the SessionEngine thread
    and only ever ENQUEUE to the connection's writer (never block);
    a dead peer raises out of the callback, which detaches this sink
    from the manager, and the server drops the connection."""

    def __init__(self, server: "SessionServer", conn: _Conn, sid: str,
                 width: int, height: int):
        self._server = server
        self._conn = conn
        self.sid = sid
        self._width = width
        self._height = height

    @property
    def want_flips(self) -> bool:
        return self._conn.want_flips

    @property
    def batch_turns(self) -> int:
        """Negotiated k-turn chunk consumption (hello "batch"): a
        positive value makes the manager hand this sink whole chunks
        via on_flip_chunk and scale the bucket's dispatch chunk."""
        return self._conn.batch if self._conn.want_flips else 0

    def on_flip_chunk(self, sid: str, first_turn: int, counts,
                      bitmaps, words) -> None:
        """One dispatched chunk for this session as _TAG_FBATCH
        frame(s) — the per-session twin of the singleton broadcaster's
        chunk fan-out: per-chunk housekeeping, shedding at batch
        granularity, encode gated after offer_stream. Stream sends run
        under the peer's seek_gate: a peer parked at a seek position
        (conn.scrub — gol_tpu.replay) is withheld the live stream, and
        the gate orders that decision against a concurrent seek's
        historical frames."""
        conn = self._conn
        if conn.lag_metric is not None:
            conn.lag_metric.set(conn.queued())
        k = len(counts)
        last = first_turn + k - 1
        self._server.freshness.note_commit(last, key=sid)
        with conn.seek_gate:
            if conn.scrub:
                return
            if conn.drained():
                conn.resync_pending = True
                mgr = self._server.manager
                self.on_sync(sid, mgr.peek_turn(sid),
                             mgr._fetch_board(sid))
                return
            if not conn.synced or last <= conn.synced_turn:
                return
            try:
                if not conn.offer_stream():
                    return
                tracing.event("turn.emit", "wire", turn=last,
                              session=sid, batch=k)
                m = accounting.meter()
                t0 = time.perf_counter() if m is not None else 0.0
                with tracing.span("wire.encode_batch", "wire", turn=last,
                                  session=sid, turns=k):
                    frames = encode_batch_frames(
                        counts, bitmaps, words, first_turn,
                        self._width, self._height, conn.batch,
                        time.time(),
                    )
                if m is not None:
                    # Host encode tax, attributed to the session this
                    # sink serves (conn.principal == sid here).
                    m.charge(conn.principal,
                             host_seconds=time.perf_counter() - t0)
                for f in frames:
                    conn.send_raw(f)
                conn.note_written(last)
            except (wire.WireError, OSError):
                self._server._drop_conn(conn, detach_sink=False)
                raise

    def on_sync(self, sid: str, turn: int, board) -> None:
        conn = self._conn
        with conn.seek_gate:
            if conn.scrub:
                return  # parked at a seek: no live resyncs either
            try:
                if conn.binary:
                    conn.send_raw(
                        wire.board_to_frame(turn, board, conn.token)
                    )
                else:
                    conn.send(wire.board_to_msg(turn, board, conn.token))
            except (wire.WireError, OSError):
                self._server._drop_conn(conn, detach_sink=False)
                raise
            conn.synced = True
            conn.synced_turn = turn
            conn.note_written(turn)
            conn.delta_prev = None
            # A degradation-coalesced resync makes the peer whole:
            # every frame it shed is inside this raster, and
            # synced_turn now gates anything still buffered.
            conn.mark_recovered()

    def on_flips(self, sid: str, turn: int, coords) -> None:
        conn = self._conn
        with conn.seek_gate:
            if conn.scrub:
                return
            if not conn.synced or turn <= conn.synced_turn:
                return
            try:
                # Sheddable stream plane: gate BEFORE encoding so a
                # shed frame never advances this peer's delta chain.
                if not conn.offer_stream():
                    return
                m = accounting.meter()
                t0 = time.perf_counter() if m is not None else 0.0
                with tracing.span("wire.encode_flips", "wire", turn=turn,
                                  session=sid):
                    _encode_and_send_flips(conn, turn, coords, None,
                                           self._width, self._height)
                if m is not None:
                    m.charge(conn.principal,
                             host_seconds=time.perf_counter() - t0)
            except (wire.WireError, OSError):
                self._server._drop_conn(conn, detach_sink=False)
                raise

    def on_turn(self, sid: str, turn: int) -> None:
        conn = self._conn
        if conn.lag_metric is not None:
            conn.lag_metric.set(conn.queued())
        self._server.freshness.note_commit(turn, key=sid)
        with conn.seek_gate:
            if conn.scrub:
                return
            if conn.drained():
                # Degraded peer drained inside the deadline: coalesce
                # the missed backlog into ONE fresh BoardSync. We are
                # on the engine thread (the device owner), after this
                # chunk's commit — the stack and `peek_turn` agree,
                # and stamping the sync with the POST-chunk turn gates
                # off the rest of this chunk's already-decoded
                # callbacks (they are inside the raster being sent;
                # re-applying would XOR-corrupt).
                conn.resync_pending = True
                mgr = self._server.manager
                self.on_sync(sid, mgr.peek_turn(sid),
                             mgr._fetch_board(sid))
                return
            if not conn.synced or turn <= conn.synced_turn:
                return
            try:
                if not conn.offer_stream():
                    return
                tracing.event("turn.emit", "wire", turn=turn, session=sid)
                conn.send({"t": "ev", "k": "turn", "turn": turn,
                           "ts": time.time()})
                conn.note_written(turn)
            except (wire.WireError, OSError):
                self._server._drop_conn(conn, detach_sink=False)
                raise

    def on_close(self, sid: str, reason: str) -> None:
        conn = self._conn
        with contextlib.suppress(Exception):
            conn.send({"t": "bye"})
        # Drain (bounded) BEFORE closing the socket: the bye must reach
        # the peer so a destroy-while-attached ends its stream cleanly
        # instead of looking like a crashed server and triggering the
        # client's reconnect storm against a session that is gone.
        conn.finish(timeout=2.0)
        self._server._drop_conn(conn, detach_sink=False)


class _SeekTarget:
    """Session-plane adapter for gol_tpu.replay.serve_seek: the
    recording's log dir, the peer's own seek_gate as the ordering
    lock (historical frames vs the live sink's sends), and the
    engine-thread live rejoin."""

    def __init__(self, server: "SessionServer", sid: str,
                 sink: _SessionSink, conn: _Conn, root: str):
        self._server = server
        self.sid = sid
        self._sink = sink
        self._conn = conn
        self.root = root
        self.lock = conn.seek_gate

    def resync_live(self, conn: _Conn) -> None:
        def _prepare():
            with conn.seek_gate:
                conn.scrub = False

        # Engine-thread verb: scrub clears and the fresh BoardSync
        # lands between dispatches, so the next chunk is contiguous
        # with the synced raster.
        self._server.manager.resync(self.sid, self._sink,
                                    prepare=_prepare)


class SessionServer:
    """The multi-tenant serving surface (gol_tpu.sessions; CLI
    `--serve --sessions`): a SessionManager + SessionEngine behind the
    same wire protocol as EngineServer, with the one-board singleton
    replaced by session multiplexing —

    - hello gains a `session` field: peers attach to a NAMED session
      (driver slot exclusive per session, observers fan out); a hello
      without one is a CONTROL peer that only speaks session verbs;
    - `{"t":"session","op":...}` verbs (create / destroy / list /
      checkpoint) from any authenticated peer, answered with
      `{"t":"session-r", ...}`;
    - per-session checkpoints under out/sessions/<id>/ compose with
      `--resume latest` (resume=True restores every session);
    - heartbeats/eviction, the clock probe, binary/delta flip frames
      and the shared-secret gate work exactly as on EngineServer —
      the peer-side protocol is unchanged above the hello."""

    HELLO_TIMEOUT = EngineServer.HELLO_TIMEOUT
    DRAIN_TIMEOUT = EngineServer.DRAIN_TIMEOUT
    HB_MISS_LIMIT = EngineServer.HB_MISS_LIMIT

    def __init__(
        self,
        params: Params,
        host: str = "127.0.0.1",
        port: int = 8030,
        *,
        secret: Optional[str] = None,
        heartbeat_secs: float = 2.0,
        evict_secs: Optional[float] = None,
        resume: bool = False,
        bucket_capacity: int = 16,
        watched_chunk: Optional[int] = None,
        idle_chunk: Optional[int] = None,
        max_peers: Optional[int] = None,
        max_sessions: Optional[int] = None,
        high_water: Optional[int] = None,
        drain_secs: Optional[float] = None,
        retry_after_secs: float = 1.0,
        batch_turns: int = 1024,
        writer_pool_threads: int = 2,
        park_idle_secs: Optional[float] = None,
        record: bool = False,
        keyframe_turns: int = 256,
        record_max_bytes: Optional[int] = None,
    ):
        from gol_tpu.sessions import SessionEngine, SessionManager

        self.params = params
        #: The same writer event loop EngineServer rides (ROADMAP item
        #: 1): session peers' frames drain through a few selector
        #: threads, not one thread per connection.
        self.pool = (WriterPool(writer_pool_threads, "gol-sess-writer")
                     if writer_pool_threads > 0 else None)
        self.batch_turns = max(0, batch_turns)
        self.heartbeat_secs = max(0.0, heartbeat_secs)
        self.evict_secs = (
            evict_secs if evict_secs is not None
            else 3.0 * self.heartbeat_secs
        )
        self._secret = secret
        #: Admission budgets + rejection hint — the EngineServer
        #: contract (docs/RESILIENCE.md "Overload & degradation"),
        #: plus a session-count budget the manager enforces at create.
        self.max_peers = max_peers
        self.high_water = high_water
        self.drain_secs = drain_secs
        self.retry_after_secs = max(0.0, retry_after_secs)
        self.manager = SessionManager(
            out_dir=params.out_dir,
            default_rule=params.rule,
            bucket_capacity=bucket_capacity,
            autosave_turns=params.autosave_turns,
            max_sessions=max_sessions,
            park_idle_secs=park_idle_secs,
        )
        #: Idempotency replay window (docs/SESSIONS.md "Idempotent
        #: verbs"): request-id -> the successful session-r reply it
        #: produced, bounded FIFO. A retried verb whose first attempt
        #: DID land (the reply was lost to a reconnect) replays the
        #: recorded answer instead of re-executing — a retried create
        #: never double-creates, a retried destroy never errors.
        self._replay: "dict[str, dict]" = {}  # insertion-ordered FIFO
        self._replay_lock = lockcheck.make_lock("SessionServer._replay_lock")
        #: Replay-plane recording (gol_tpu.replay, docs/REPLAY.md):
        #: with `record`, every live session gets an ephemeral
        #: RecorderSink taping its encoded wire stream into
        #: out/sessions/<sid>/replay/, and the `seek` verb serves
        #: time-travel from those logs.
        self.record = bool(record)
        self.keyframe_turns = max(1, int(keyframe_turns))
        self.record_max_bytes = record_max_bytes
        self._recorders: "dict[str, object]" = {}
        self._recorder_lock = lockcheck.make_lock(
            "SessionServer._recorder_lock")
        if self.record:
            # Recording state rides the session.json sidecar (the
            # PR 7 crash-consistency story covers it), and the
            # recorder factory makes EVERY create — wire verb, resume,
            # rehydration — tape from its first turn (a resumed
            # session's fresh keyframe also CUTS any stale future
            # segments a dead incarnation recorded past its last
            # checkpoint: SegmentLog.start_segment). Import the plane
            # now so the first create doesn't pay module-import
            # latency inside an engine verb.
            import gol_tpu.replay.recorder  # noqa: F401

            self.manager.record_meta = {
                "keyframe_turns": self.keyframe_turns,
            }
            self.manager.recorder_factory = self._make_recorder
        #: Sessions restored from out/sessions/ at boot (PR 3's
        #: `--resume latest`, composed per session).
        self.resumed = self.manager.resume_all() if resume else 0
        self.engine = SessionEngine(self.manager,
                                    watched_chunk=watched_chunk,
                                    idle_chunk=idle_chunk)
        self._listener = socket.create_server((host, port))
        self.address = self._listener.getsockname()
        publish_listen_addr(self.address)
        #: Freshness plane: per-peer turn age against each SESSION's
        #: own committed turn (clocks keyed by sid — one stalled
        #: session can never age another session's watchers).
        self.freshness = ServerFreshness("session")
        self._conn_lock = lockcheck.make_lock("SessionServer._conn_lock")
        self._conns: "list[_Conn]" = []
        #: sid -> driving connection (one driver per session).
        self._drivers: "dict[str, _Conn]" = {}
        #: conn -> (sid, sink) for session-attached peers.
        self._sinks: "dict[_Conn, tuple[str, _SessionSink]]" = {}
        self._shutdown = threading.Event()
        self.done = threading.Event()
        self._threads: "list[threading.Thread]" = []
        #: Drain verb (control plane, PR 18): once set, every live
        #: session has a fresh checkpoint on disk and NEW session
        #: attaches are refused — the safe prelude to a rolling
        #: restart with `--resume latest`. Plain bool, GIL-atomic:
        #: read on the accept path, written by the verb.
        self.draining = False

    # --- lifecycle ---

    def start(self) -> "SessionServer":
        self.engine.start()
        loops = [(self._accept_loop, "gol-sess-accept")]
        if self.heartbeat_secs > 0:
            loops.append((self._heartbeat_loop, "gol-sess-heartbeat"))
        for fn, name in loops:
            t = threading.Thread(target=fn, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def shutdown(self) -> None:
        if self._shutdown.is_set():
            self.done.wait(timeout=1.0)
            return
        self._shutdown.set()
        with contextlib.suppress(OSError):
            # SHUT_RDWR first: on Linux, close() alone does NOT wake a
            # thread parked in accept() — the zombie accept holds the
            # LISTEN socket alive and the port stays bound, so an
            # in-process restart on the same address gets EADDRINUSE.
            self._listener.shutdown(socket.SHUT_RDWR)
        with contextlib.suppress(OSError):
            self._listener.close()
        # Close sinks through the manager first (each attached peer
        # gets its bye in-stream), then stop the dispatch loop.
        with contextlib.suppress(Exception):
            self.manager.close()
        self.engine.stop()
        self.engine.join(timeout=30)
        with self._conn_lock:
            conns, self._conns = list(self._conns), []
            self._drivers.clear()
            self._sinks.clear()
        for conn in conns:
            with contextlib.suppress(Exception):
                conn.send({"t": "bye"})
            conn.request_finish()
        deadline = time.monotonic() + self.DRAIN_TIMEOUT
        for conn in conns:
            conn.join_writer(max(0.1, deadline - time.monotonic()))
            conn.close()
        if self.pool is not None:
            self.pool.close()
        self.freshness.close()
        self.done.set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self.done.wait(timeout)

    def health(self) -> dict:
        info = self.engine.health()
        with self._conn_lock:
            info["peers"] = len(self._conns)
        info["address"] = list(self.address)
        if self.draining:
            info["draining"] = True
        if self._shutdown.is_set() and info.get("status") == "ok":
            info["status"] = "shutting-down"
        return info

    # --- accept path ---

    def _accept_loop(self) -> None:
        from gol_tpu.testing import faults

        while not self._shutdown.is_set():
            try:
                sock, addr = self._listener.accept()
            except OSError:
                return  # listener closed
            sock = faults.wrap("server", sock)
            _METRICS.accepts.inc()
            try:
                sock.settimeout(self.HELLO_TIMEOUT)
                hello = wire.recv_msg(sock, allow_binary=False)
                if not hello or hello.get("t") != "hello":
                    raise wire.WireError(f"bad hello: {hello!r}")
            except (wire.WireError, OSError, ValueError) as e:
                log.warning("rejecting connection from %s: %s", addr, e)
                _METRICS.rejects["bad-hello"].inc()
                sock.close()
                continue
            if self._secret is not None and not hmac.compare_digest(
                str(hello.get("secret", "")).encode("utf-8", "replace"),
                self._secret.encode("utf-8", "replace"),
            ):
                log.warning("rejecting unauthenticated attach from %s",
                            addr)
                _METRICS.rejects["unauthorized"].inc()
                with contextlib.suppress(Exception):
                    wire.send_msg(
                        sock, {"t": "error", "reason": "unauthorized"}
                    )
                sock.close()
                continue
            self._admit(sock, hello)

    def _admit(self, sock: socket.socket, hello: dict) -> None:
        from gol_tpu.sessions import SessionError, valid_session_id

        if (self.max_peers is not None
                and len(self._conns) >= self.max_peers):
            # Admission control (docs/RESILIENCE.md): a full house
            # sheds the attach at the door with a when-to-come-back
            # hint the client backoff honors.
            _METRICS.rejects["at-capacity"].inc()
            with contextlib.suppress(Exception):
                wire.send_msg(sock, {
                    "t": "error", "reason": "at-capacity",
                    "retry_after": self.retry_after_secs,
                })
            sock.close()
            return
        role = ("observe" if hello.get("role") == "observe" else "drive")
        sid = hello.get("session")
        if sid is not None and self.draining:
            # A drained server is about to restart (control plane
            # roll): session attaches bounce with a come-back hint —
            # the client backoff rides the restart gap and resumes
            # through BoardSync on the fresh incarnation. Bare control
            # connections stay admitted (operators still list/verb).
            _METRICS.rejects["draining"].inc()
            with contextlib.suppress(Exception):
                wire.send_msg(sock, {
                    "t": "error", "reason": "draining",
                    "retry_after": self.retry_after_secs,
                })
            sock.close()
            return
        if sid is not None and (
            not valid_session_id(sid) or not self.manager.known(sid)
        ):
            with contextlib.suppress(Exception):
                wire.send_msg(
                    sock, {"t": "error", "reason": "unknown-session"}
                )
            sock.close()
            return
        hb = bool(hello.get("hb", False)) and self.heartbeat_secs > 0
        conn = _Conn(sock, bool(hello.get("want_flips", False)),
                     compact=bool(hello.get("compact", False)),
                     binary=bool(hello.get("binary", False)),
                     levels=bool(hello.get("levels", False)),
                     role=role, hb=hb,
                     delta=bool(hello.get("delta", False)),
                     batch=_clamp_batch(hello, self.batch_turns),
                     high_water=self.high_water,
                     drain_secs=self.drain_secs,
                     pool=self.pool)
        if sid is not None:
            # Session-attached peers bill to their TENANT, not the
            # transient socket: everything this connection moves or
            # occupies joins the session's usage record (the same
            # principal the manager charges dispatch shares to).
            conn.principal = sid
        if sid is not None and role == "drive":
            with self._conn_lock:
                busy = sid in self._drivers
                if not busy:
                    self._drivers[sid] = conn
            if busy:
                _METRICS.rejects["busy"].inc()
                with contextlib.suppress(Exception):
                    wire.send_msg(sock, {
                        "t": "error", "reason": "busy",
                        "retry_after": self.retry_after_secs,
                    })
                sock.close()
                return
        with self._conn_lock:
            self._conns.append(conn)
            _METRICS.peers.set(len(self._conns))
        _METRICS.attaches[role].inc()
        install_lag_gauge(conn)
        ack = {"t": "attach-ack", "clock": True, "sessions": True,
               "depth": 0}
        if conn.batch:
            ack["batch"] = conn.batch
        if sid is not None:
            ack["session"] = sid
        if hb:
            ack["hb_secs"] = self.heartbeat_secs
        try:
            conn.send(ack)
        except (wire.WireError, OSError):
            self._drop_conn(conn)
            return
        try:
            conn.start_writer(self._drop_conn)
        except wire.WireError:
            self._drop_conn(conn)
            return
        tracing.event("server.attach", "lifecycle", role=role,
                      token=conn.token, session=sid)
        flight.note("server.attach", role=role, token=conn.token,
                    session=sid)
        # Reader BEFORE the sink attach: manager.attach blocks on the
        # engine thread (a cold bucket compile can hold it for tens of
        # seconds), and heartbeat pongs arriving in that window must
        # be READ or the liveness judge evicts a perfectly live peer —
        # beacons were already flowing (the writer is up), so the
        # pongs are already coming back.
        threading.Thread(
            target=self._reader_loop, args=(conn,),
            name="gol-sess-reader", daemon=True,
        ).start()
        if sid is not None:
            geom = self.manager.peek_geometry(sid) or (0, 0)
            sink = _SessionSink(self, conn, sid, geom[0] or 0,
                                geom[1] or 0)
            # Register the sink BEFORE the (possibly slow) attach: a
            # peer that sends a seek verb the instant its board sync
            # lands must find its session mapping, not race the
            # registration into a spurious "not-recorded". Every
            # failure path below goes through _drop_conn, which pops
            # the entry (and detaches the sink OUTSIDE _conn_lock —
            # manager.detach blocks on the engine verb queue, and the
            # engine thread may simultaneously be tearing a sink down
            # through on_close -> _drop_conn, which needs _conn_lock:
            # holding it across the verb deadlocks the serving plane,
            # seen live as a ~60s stall).
            with self._conn_lock:
                gone = conn not in self._conns
                if not gone:
                    self._sinks[conn] = (sid, sink)
            if gone:  # reader dropped the peer before we got here
                return
            try:
                # A parked session rehydrates inside attach — the
                # board sync below then carries the revived state
                # (docs/SESSIONS.md "Hibernation").
                self.manager.attach(sid, sink)
            except (wire.WireError, OSError):
                # The peer died during its own board sync: its slot is
                # already released (on_sync drops the conn); the accept
                # thread must survive.
                self._drop_conn(conn)
                return
            except (SessionError, TimeoutError) as e:
                # Destroyed between the hello check and the attach —
                # or a rehydration the resident budget refused: the
                # real reason (with a retry hint on transient ones)
                # lets the client back off instead of giving up.
                reason = (str(e) if isinstance(e, SessionError)
                          else "busy")
                err = {"t": "error", "reason": reason}
                if reason in ("max-sessions", "busy"):
                    err["retry_after"] = self.retry_after_secs
                with contextlib.suppress(Exception):
                    conn.send(err)
                self._drop_conn(conn)
                return
            undo = False
            with self._conn_lock:
                if conn not in self._conns:
                    # The reader dropped the peer ('q', death) while we
                    # were attaching; _drop_conn already popped _sinks
                    # — undo the manager-side attach it could not have
                    # seen yet.
                    undo = True
            if undo:
                with contextlib.suppress(Exception):
                    self.manager.detach(sid, sink)

    # --- replay-plane recording + seek (gol_tpu.replay) ---

    def _make_recorder(self, sid: str, width: int, height: int):
        """The manager's recorder factory (called from inside _create,
        on the owner thread): one RecorderSink per live session,
        taping into out/sessions/<sid>/replay/. Returns None when the
        session already has one (re-entrant resume paths)."""
        import os

        from gol_tpu.checkpoint import session_checkpoint_dir
        from gol_tpu.replay.log import SegmentLog, replay_dir
        from gol_tpu.replay.recorder import RecorderSink

        with self._recorder_lock:
            if sid in self._recorders:
                return None
            d = replay_dir(os.path.join(
                session_checkpoint_dir(self.manager.out_dir), sid
            ))
            try:
                rec = RecorderSink(
                    self.manager, sid, width, height,
                    SegmentLog(d, keyframe_turns=self.keyframe_turns,
                               max_bytes=self.record_max_bytes),
                    on_closed=self._recorder_closed,
                )
            except OSError:
                log.exception("recorder for session %r failed to open",
                              sid)
                return None
            self._recorders[sid] = rec
        return rec

    def _recorder_closed(self, sid: str, reason: str) -> None:
        with self._recorder_lock:
            self._recorders.pop(sid, None)

    def _handle_seek(self, conn: _Conn, msg: dict) -> None:
        """One `{"t":"seek"}` verb on the session plane: time-travel
        served from the session's recording under the idempotent-rid
        rules (gol_tpu.replay.serve_seek — the shared implementation;
        the reply is sent AFTER the frames, as the completion
        marker)."""
        from gol_tpu.replay.server import serve_seek

        with self._conn_lock:
            entry = self._sinks.get(conn)
        target = None
        if entry is not None:
            sid, sink = entry
            with self._recorder_lock:
                rec = self._recorders.get(sid)
            if rec is not None:
                target = _SeekTarget(self, sid, sink, conn,
                                     rec.log.root)
        try:
            reply = serve_seek(conn, msg, target,
                               replay_lookup=self._replay_lookup,
                               replay_record=self._replay_record)
        except (wire.WireError, OSError):
            self._drop_conn(conn)
            return
        with contextlib.suppress(wire.WireError, OSError):
            conn.send(reply)

    def _drop_conn(self, conn: _Conn, detach_sink: bool = True) -> None:
        """Remove one peer everywhere (idempotent; any thread). With
        `detach_sink` the manager-side sink is detached too — callbacks
        already running inside the manager pass False (the manager is
        removing the sink itself)."""
        with self._conn_lock:
            removed = conn in self._conns
            if removed:
                self._conns.remove(conn)
            entry = self._sinks.pop(conn, None)
            for sid, c in list(self._drivers.items()):
                if c is conn:
                    del self._drivers[sid]
            _METRICS.peers.set(len(self._conns))
        if removed:
            _METRICS.detaches.inc()
            remove_lag_gauge(conn)
            self.freshness.forget(conn.token)
            _forget_peer_usage(conn)
            tracing.event("server.detach", "lifecycle", role=conn.role,
                          token=conn.token)
        if entry is not None and detach_sink and not self._shutdown.is_set():
            sid, sink = entry
            with contextlib.suppress(Exception):
                self.manager.detach(sid, sink)
        conn.close()

    # --- peer → server ---

    def _reader_loop(self, conn: _Conn) -> None:
        while True:
            try:
                msg = wire.recv_msg(conn.sock, allow_binary=False)
            except TimeoutError:
                if conn._dead.is_set():
                    self._drop_conn(conn)
                    return
                continue
            except (wire.WireError, OSError):
                msg = None
            if msg is None:
                self._drop_conn(conn)
                return
            conn.last_rx = time.monotonic()
            conn.hb_unanswered = 0
            t = msg.get("t")
            if t == "clk":
                with contextlib.suppress(wire.WireError, OSError):
                    conn.send_direct({"t": "clk", "t0": msg.get("t0"),
                                      "ts": time.time()})
                continue
            if t == "session":
                self._handle_session_op(conn, msg)
                continue
            if t == "seek":
                # Time-travel verb (gol_tpu.replay): read-only, so
                # observers may scrub too.
                self._handle_seek(conn, msg)
                continue
            if t != "key":
                continue
            if not self._handle_key(conn, msg.get("key")):
                return

    def _handle_key(self, conn: _Conn, key) -> bool:
        """Session-mode verb routing; False ends the reader loop."""
        with self._conn_lock:
            entry = self._sinks.get(conn)
        if key == "q":
            if entry is not None:
                sid, sink = entry
                with contextlib.suppress(Exception):
                    self.manager.detach(sid, sink)
            self._release_slot(conn)
            with contextlib.suppress(Exception):
                conn.send({"t": "detached"})
            conn.finish()
            self._drop_conn(conn, detach_sink=False)
            return False
        if key == "s" and entry is not None and conn.role == "drive":
            # The snapshot verb, scoped to this peer's session.
            from gol_tpu.sessions import SessionError

            with contextlib.suppress(SessionError, TimeoutError):
                self.manager.checkpoint(entry[0])
            return True
        with contextlib.suppress(Exception):
            conn.send({"t": "error",
                       "reason": ("observer" if conn.role == "observe"
                                  else "unsupported")})
        return True

    def _release_slot(self, conn: _Conn) -> None:
        with self._conn_lock:
            self._sinks.pop(conn, None)
            for sid, c in list(self._drivers.items()):
                if c is conn:
                    del self._drivers[sid]

    #: Bounded replay window for idempotent verbs: enough rids for
    #: hundreds of in-flight retries across reconnects; old entries
    #: age out FIFO (a retry arriving after 512 newer verbs falls back
    #: to the state-based idempotency checks, which are still exact).
    REPLAY_WINDOW = 512

    def _replay_lookup(self, rid: str) -> Optional[dict]:
        with self._replay_lock:
            return self._replay.get(rid)

    def _replay_record(self, rid: str, reply: dict) -> None:
        with self._replay_lock:
            self._replay[rid] = reply
            while len(self._replay) > self.REPLAY_WINDOW:
                del self._replay[next(iter(self._replay))]

    def _idempotent_outcome(self, op, msg: dict, reason: str,
                            reply: dict) -> bool:
        """State-based idempotency for RETRIED verbs (rid present):
        when the failure reason says the operation's effect is already
        in place, answer ok instead of erroring the retry. This is the
        layer that survives a server restart (the replay window does
        not): a create that committed before a SIGKILL answers
        `exists` after `--resume latest`, and an identical-recipe
        retry must read that as success, not a duplicate."""
        if op == "destroy" and reason == "unknown-session":
            # Destroyed by the first attempt (or by anyone): the
            # desired end state — absence — holds.
            reply.update(ok=True, id=msg.get("id"), replayed=True)
            return True
        if op == "park" and reason == "parked":
            # Parked by the first attempt (or the idle sweep): the
            # desired end state — hibernated — holds.
            reply.update(
                ok=True, id=msg.get("id"),
                turn=self.manager.peek_turn(msg.get("id")),
                replayed=True,
            )
            return True
        if op == "adopt" and reason == "exists":
            # A retried adopt whose first attempt landed (or a
            # controller resume re-issuing a committed migration leg):
            # success iff the resident/parked session matches the
            # SOURCE sidecar's geometry+rule — a pre-existing
            # different session under the same id stays a real
            # duplicate.
            import os as _os

            from gol_tpu.checkpoint import session_checkpoint_dir

            sid = msg.get("id")
            info = next(
                (i for i in self.manager.list_sessions()
                 if i["id"] == sid), None)
            if info is None:
                return False
            try:
                with open(_os.path.join(
                    session_checkpoint_dir(str(msg.get("source"))),
                    sid, "session.json",
                )) as f:
                    side = json.load(f)
                same = (
                    info.get("width") == int(side["width"])
                    and info.get("height") == int(side["height"])
                    and str(info.get("rule")) == str(side.get("rule"))
                )
            except (OSError, ValueError, KeyError, TypeError):
                return False
            if not same:
                return False
            reply.update(ok=True, session=info, replayed=True)
            return True
        if op == "create" and reason == "exists":
            from gol_tpu.models.rules import get_rule

            sid = msg.get("id")
            s = self.manager.get(sid)
            if s is None:
                # The first attempt's create may have landed and been
                # hibernated by the idle sweep before the retry
                # arrived: an IDENTICAL recipe — seed/density
                # included, exactly the live compare below — still
                # reads as success; anything else is a real duplicate.
                meta = self.manager.parked_meta(sid)
                if meta is None:
                    return False
                try:
                    want_rule = (self.manager.default_rule
                                 if msg.get("rule") is None
                                 else get_rule(msg["rule"]))
                    same = (
                        meta.get("width") == msg.get("width")
                        and meta.get("height") == msg.get("height")
                        and str(meta.get("rule")) == str(want_rule)
                        and meta.get("seed") == msg.get("seed")
                        and (meta.get("seed") is None
                             or meta.get("density")
                             == float(msg.get("density", 0.25)))
                    )
                except (ValueError, TypeError):
                    return False
                if not same:
                    return False
                info = next(
                    (i for i in self.manager.list_sessions()
                     if i["id"] == sid), None)
                reply.update(ok=True, session=info, replayed=True)
                return True
            b = s.bucket
            try:
                want_rule = (self.manager.default_rule
                             if msg.get("rule") is None
                             else get_rule(msg["rule"]))
                same = (
                    b.width == msg.get("width")
                    and b.height == msg.get("height")
                    and str(b.rule) == str(want_rule)
                    and s.seed == msg.get("seed")
                    and (s.seed is None
                         or s.density == float(msg.get("density", 0.25)))
                )
            except (ValueError, TypeError):
                return False
            if not same:
                return False  # a REAL duplicate id, not a retry
            reply.update(ok=True, session=s.info(), replayed=True)
            return True
        return False

    def _handle_session_op(self, conn: _Conn, msg: dict) -> None:
        """One `{"t":"session"}` verb; every outcome is an in-stream
        `session-r` reply — a malformed request must never kill the
        reader or wedge the peer waiting. Verbs stamped with a client
        request id (`rid`) are idempotent: a completed verb's reply is
        replayed from the bounded window, and state-based checks make
        retried creates/destroys converge even when the window (or the
        whole process) has been lost in between."""
        from gol_tpu.sessions import SessionError

        op = msg.get("op")
        rid = msg.get("rid")
        if not (isinstance(rid, str) and 0 < len(rid) <= 128):
            rid = None  # absent or hostile: plain one-shot semantics
        if rid is not None:
            cached = self._replay_lookup(rid)
            if cached is not None:
                with contextlib.suppress(wire.WireError, OSError):
                    conn.send(cached)
                return
        reply = {"t": "session-r", "op": op}
        if rid is not None:
            reply["rid"] = rid
        try:
            if op == "create":
                density = msg.get("density", 0.25)
                info = self.manager.create(
                    msg.get("id"),
                    width=msg.get("width"), height=msg.get("height"),
                    rule=msg.get("rule"), seed=msg.get("seed"),
                    density=float(density),
                )
                reply.update(ok=True, session=info)
            elif op == "destroy":
                self.manager.destroy(msg.get("id"))
                # Evict the destroyed session's freshness clock (the
                # bounded-cardinality discipline: clocks key on sid
                # and must not accumulate under create/destroy churn).
                self.freshness.drop_key(msg.get("id"))
                reply.update(ok=True, id=msg.get("id"))
            elif op == "list":
                reply.update(ok=True,
                             sessions=self.manager.list_sessions())
            elif op == "checkpoint":
                r = self.manager.checkpoint(msg.get("id"))
                reply.update(ok=True, id=msg.get("id"), **r)
            elif op == "park":
                r = self.manager.park(msg.get("id"))
                reply.update(ok=True, **r)
            elif op == "adopt":
                # Control-plane migration (PR 18): materialize a
                # session parked under ANOTHER engine's out tree. The
                # manager re-checkpoints locally before this acks.
                info = self.manager.adopt(msg.get("id"),
                                          msg.get("source"))
                reply.update(ok=True, session=info)
            elif op == "drain":
                n = self._drain()
                reply.update(ok=True, checkpointed=n, draining=True)
            else:
                reply.update(ok=False, reason="unknown-op")
        except SessionError as e:
            reason = str(e)
            if not (rid is not None
                    and self._idempotent_outcome(op, msg, reason, reply)):
                reply.update(ok=False, reason=reason)
                if reason == "max-sessions":
                    # Over-budget is transient by design: tell the
                    # storm when to come back instead of letting it
                    # hammer a full house.
                    reply["retry_after"] = self.retry_after_secs
        except (TypeError, ValueError, KeyError):
            reply.update(ok=False, reason="bad-request")
        except TimeoutError:
            reply.update(ok=False, reason="busy",
                         retry_after=self.retry_after_secs)
        except OSError:
            # Manifest/tombstone/checkpoint writes hit the filesystem:
            # a full or read-only disk must answer the verb (the
            # effect may or may not have committed — the rid retry
            # discipline handles that), never kill the reader thread
            # and leak a conn that consumes an admission slot forever.
            log.exception("session verb %r failed on I/O", op)
            reply.update(ok=False, reason="io-error")
        if rid is not None and reply.get("ok"):
            self._replay_record(rid, reply)
        with contextlib.suppress(wire.WireError, OSError):
            conn.send(reply)

    def _drain(self) -> int:
        """The roll verb's first half (control plane, PR 18):
        checkpoint every RESIDENT session crash-atomically and flip
        the draining flag so new session attaches bounce with a
        retry hint. After this acks, a SIGTERM + `--resume latest`
        restart loses nothing — parked sessions already sit on their
        hibernation snapshots. Idempotent by construction: a retried
        drain re-checkpoints (same turn, same bytes) and stays
        draining. Returns the number checkpointed."""
        from gol_tpu.sessions import SessionError

        self.draining = True
        n = 0
        for info in self.manager.list_sessions():
            if info.get("parked"):
                continue
            with contextlib.suppress(SessionError, TimeoutError,
                                     OSError):
                self.manager.checkpoint(info["id"])
                n += 1
        tracing.event("server.drain", "lifecycle", checkpointed=n)
        flight.note("server.drain", checkpointed=n)
        return n

    # --- liveness (the EngineServer discipline, per session) ---

    def _heartbeat_loop(self) -> None:
        interval = max(0.05, self.heartbeat_secs / 2.0)
        while not self._shutdown.wait(interval):
            now = time.monotonic()
            with self._conn_lock:
                conns = list(self._conns)
                sids = dict((c, s[0]) for c, s in self._sinks.items())
            # Freshness sweep: session-attached peers age against
            # THEIR session's clock; control peers (no sink) are not
            # stream consumers and are skipped.
            self.freshness.sample(
                (c, sids[c]) for c in conns if c in sids
            )
            # Accounting sweep (same rationale as the EngineServer's):
            # writer-queue occupancy in frame-seconds per principal.
            _meter = accounting.meter()
            if _meter is not None:
                for c in conns:
                    q = c.queued()
                    if q:
                        _meter.charge(c.principal,
                                      queue_frame_seconds=q * interval)
            for conn in conns:
                if not conn.writer_started:
                    continue
                if conn.degraded:
                    # Degradation owns this peer's verdict (the
                    # EngineServer discipline): no beacons into a
                    # backlogged queue, no hb-eviction racing the
                    # drain deadline. Drain-resync happens on the
                    # engine thread (the sink's on_turn — it needs the
                    # device); this loop only enforces the deadline.
                    if (now - conn.degraded_since > conn.drain_secs
                            and conn.queued() > conn.LOW_WATER):
                        log.warning(
                            "evicting session peer %d: wedged %.1fs "
                            "past the drain deadline", conn.token,
                            now - conn.degraded_since,
                        )
                        if conn.count_overflow():
                            _METRICS.overflows.inc()
                            flight.note("server.drain_evict",
                                        token=conn.token)
                        self._drop_conn(conn)
                    continue
                if (conn.hb and conn.hb_unanswered >= self.HB_MISS_LIMIT
                        and now - conn.last_rx > self.evict_secs):
                    log.warning(
                        "evicting unresponsive session peer (silent "
                        "%.1fs)", now - conn.last_rx,
                    )
                    _METRICS.evicted.inc()
                    tracing.event("server.evict", "lifecycle",
                                  role=conn.role, token=conn.token)
                    flight.note("server.evict", role=conn.role,
                                token=conn.token)
                    self._drop_conn(conn)
                    flight.dump("peer-eviction")
                    continue
                if now - conn.last_tx >= self.heartbeat_secs:
                    # peek_turn, NOT manager.get: the manager lock is
                    # held across whole bucket dispatches (cold
                    # compiles included) and a beacon that waits on it
                    # defeats its own purpose — liveness must stay
                    # engine-loop independent (docs/RESILIENCE.md).
                    turn = self.manager.peek_turn(sids.get(conn, ""))
                    try:
                        if conn.binary:
                            conn.send_raw(wire.heartbeat_to_frame(turn))
                        else:
                            conn.send({"t": "hb", "turn": turn})
                    except (wire.WireError, OSError):
                        self._drop_conn(conn)
                        continue
                    _METRICS.heartbeats.inc()
                    if conn.hb:
                        conn.hb_unanswered += 1
