"""Wire protocol for the controller ⇄ engine link.

The reference intended `net/rpc` over TCP between controller, broker and
engine workers but shipped only dead stubs (ref: gol/distributor.go:44-52,
459-530; topology spec ref: README.md:201-207). This is the working
equivalent: length-prefixed JSON messages over a stream socket — a
control plane carrying events, keys and board syncs. (The *data plane* —
halo exchange, alive-count reductions — never touches this layer: it is
XLA collectives over ICI inside the step program, see parallel/halo.py.)

Framing: 4-byte big-endian payload length, then either a UTF-8 JSON
object (control plane: hello, keys, events, acks — every message has a
"t" discriminator) or a BINARY frame whose first byte is a tag < 0x20
(bulk plane: flips, board rasters, final alive sets — raw header +
zlib payload, no base64). JSON payloads always start with '{' (0x7b),
so the tag byte is also the discriminator: receivers decode either
kind without negotiation. SENDING binary is negotiated — a peer
advertises `"binary": true` in its hello, legacy peers keep getting
base64-inside-JSON. The base64 layer was a measured ~33% byte
inflation on a path that is link-bound (VERDICT r4 Weak #4:
wire_watched ran at the device-link bound, ~10-12 MB/s).

Message catalog:
  controller → engine:
    {"t":"hello","want_flips":bool[,"secret":s][,"compact":bool]
                 [,"binary":bool][,"batch":K][,"session":id]
                 [,"sessions":true]}
        attach + subscription (the secret authenticates when the server
        was started with one — the reference's :8030 listener was open
        to any peer, ref: gol/distributor.go:49-52; that is a flaw to
        beat. "compact" advertises the zlib'd flips encoding; "binary"
        the raw tag+header+zlib frames; servers send legacy JSON to
        peers that advertise neither. "session" targets a NAMED session
        on a multi-tenant `--serve --sessions` server — unknown ids are
        rejected with {"t":"error","reason":"unknown-session"}; a hello
        with neither "session" nor a singleton board behind it is a
        CONTROL peer that only speaks the session verbs below.)
    {"t":"key","key":"p|s|q|k"}       keyboard verb (ref: sdl/loop.go:18-27)
  session verbs (gol_tpu.sessions; either direction is JSON-only —
  docs/SESSIONS.md):
    {"t":"session","op":"create","id":s,"width":W,"height":H
                   [,"rule":r][,"seed":n][,"density":f]}
    {"t":"session","op":"destroy"|"checkpoint","id":s}
    {"t":"session","op":"list"}
        any authenticated peer may manage sessions; every request is
        answered in-stream by
    {"t":"session-r","op":...,"ok":bool[,"reason":s][,"session":{...}]
                     [,"sessions":[...]][,"path":p][,"turn":N]}
        failure reasons are single tokens ("exists", "unknown-session",
        "bad-dimensions", "bad-rule", "bad-request", ...) — the fuzz
        suite pins that a malformed verb gets a reasoned rejection,
        never a dead reader thread.
  engine → controller:
    {"t":"board","turn":N,"width":W,"height":H,"data":b64}  attach sync
    {"t":"flips","turn":N,"cells_z":b64}                    per-turn diff
        (zlib'd int32 x,y pairs — the board-raster treatment; plain
        JSON "cells":[[x,y],...] is still DECODED for back-compat)
    delta-of-sparse flips (binary tag 6, negotiated via hello "delta"):
        per-turn CHANGED-WORD frame instead of cell coords — the
        changed-word bitmap XORed against the previous sent turn's
        bitmap (settled boards revisit the same active words, so the
        delta zlibs to near nothing) plus the changed words' XOR masks
        themselves, both zlib-bounded. The chain resets at every
        BoardSync on both ends; turns with no flips send no frame and
        do not advance the chain. VERDICT r5 item 7, productized
        behind the byte measurement in BENCH_DETAIL `wire_delta_sparse`.
    k-turn flip batches (binary tag 7, negotiated via hello "batch":
    max-k; requires "binary"):
        ONE frame carries up to max-k turns of changed-word XOR masks,
        delta-compressed along the TURN axis: turn i's changed-word set
        rides as D[i] = S[i] XOR S[i-1] (D[0] = S[0] raw), so a settled
        board — where consecutive turns flip the same cells — collapses
        to one turn's payload per batch. Frames are SELF-CONTAINED (the
        first turn always ships raw), which is how the delta chain
        "resets" at BoardSync: no encoder/decoder state ever crosses a
        frame, so a resync can never decode against a stale chain (the
        property _TAG_DFLIPS maintains by explicit per-peer resets).
        The header stamps the batch's emit wall clock once — turn
        latency is measured emit-of-batch → apply-of-batch
        (gol_tpu_client_batch_latency_seconds, NOT the per-turn
        histogram: docs/OBSERVABILITY.md "Batch latency semantics").
        This frame is the watched-path throughput fix (ROADMAP item 1):
        per-turn frames cap a watched 512² session at ~300 turns/s;
        batch frames lift it past 100k (BENCH_DETAIL
        `wire_watched_512x512_batch`).
    {"t":"ev", ...}                   one serialized Event (below)
    {"t":"detached"}                  'q' acknowledged; engine lives on
    {"t":"bye"}                       stream over (final turn or 'k')
  either direction (liveness — docs/RESILIENCE.md):
    {"t":"hb","turn":N}               server heartbeat, sent when a
        peer's stream has been idle past the heartbeat interval (binary
        peers get the raw-tag form); the client answers with a JSON
        {"t":"hb"} pong, which is what refreshes the server's
        idle-eviction clock. Peers that predate the frame ignore it
        (unknown kinds are ignorable on both sides).
  clock probe (docs/OBSERVABILITY.md — negotiated via the attach-ack's
  "clock" key; legacy peers on either side just never exchange these):
    {"t":"clk","t0":T}                controller ping carrying its wall
        clock; the server echoes {"t":"clk","t0":T,"ts":S} immediately
        and QUEUE-FREE with its own wall clock, giving the client an
        NTP-style offset sample bounded by RTT/2 — the min-RTT sample
        becomes gol_tpu_client_clock_offset_seconds and corrects the
        turn-latency math and merged timelines.
"""

from __future__ import annotations

import base64
import json
import math
import socket
import struct
import zlib
from typing import Optional

import numpy as np

from gol_tpu.events import (
    AliveCellsCount,
    CellFlipped,
    Event,
    FinalTurnComplete,
    ImageOutputComplete,
    State,
    StateChange,
    TurnComplete,
)
from gol_tpu.obs import tracing
from gol_tpu.utils.cell import Cell

MAX_FRAME = 64 << 20
#: Decompressed-payload ceiling. The frame cap bounds *compressed*
#: size only; a hostile or buggy peer could otherwise make a receiver
#: allocate multi-GB buffers from a 64 MiB zlib bomb (ADVICE r4). 512
#: MiB covers every legitimate payload (an 8192² raster is 64 MiB raw;
#: a full-board flip of int32 pairs on the same board is 512 MiB) —
#: callers that know the exact expected size pass a tighter limit.
MAX_RAW = 512 << 20
_LEN = struct.Struct(">I")


class WireError(ConnectionError):
    pass


def _decompress(data: bytes, limit: Optional[int] = None) -> bytes:
    """zlib-decompress with a hard output bound (never trusts the
    peer's sizes — see MAX_RAW, read at call time so the ceiling is
    one live module attribute, not a def-time snapshot)."""
    if limit is None:
        limit = MAX_RAW
    d = zlib.decompressobj()
    out = d.decompress(data, limit)
    if d.unconsumed_tail:
        raise WireError(f"decompressed payload exceeds {limit} bytes")
    if not d.eof:
        # zlib.decompress would raise on an incomplete stream; the
        # incremental object just stops — surface truncation/corruption
        # instead of returning a silently partial payload.
        raise WireError("truncated zlib stream")
    return out


def frame_bytes(payload: bytes) -> bytes:
    """Length-prefix one raw payload — the on-wire form of a frame.
    The writer pool queues these (already framed, so a pool thread
    never touches the encoding layer); `send_frame` is the blocking
    twin for direct sends."""
    if len(payload) > MAX_FRAME:
        raise WireError(f"frame too large: {len(payload)} bytes")
    return _LEN.pack(len(payload)) + payload


def send_frame(sock: socket.socket, payload: bytes) -> None:
    """Length-prefix and send one raw payload (binary frame or encoded
    JSON) — the single sender both planes share."""
    sock.sendall(frame_bytes(payload))
    # One instant mark per frame at THE send chokepoint both planes
    # share — the wire hop of the session timeline (gol_tpu.obs.tracing;
    # a no-op flag read when the plane is off).
    tracing.event("wire.send", "wire", bytes=len(payload))


def send_msg(sock: socket.socket, msg: dict) -> None:
    send_frame(sock, json.dumps(msg, separators=(",", ":")).encode())


def recv_msg(sock: socket.socket,
             allow_binary: bool = True) -> Optional[dict]:
    """Next message, or None on clean EOF at a frame boundary. Binary
    frames decode to the same dict shapes the JSON forms produce, with
    payloads already parsed (see _parse_frame) — consumers dispatch on
    "t" either way. Every malformed payload raises WireError (JSON
    included: a JSONDecodeError escaping here would kill reader
    threads whose handlers expect WireError/OSError only).

    `allow_binary=False` rejects bulk frames WITHOUT parsing them —
    the engine server's receive side (hellos, key verbs) is
    JSON-only, and refusing early means an unauthenticated peer can
    never make the server inflate a zlib payload (the bulk decoders
    allocate up to MAX_RAW on legitimate frames).

    Sockets carrying a read deadline (settimeout — the liveness
    discipline of docs/RESILIENCE.md) surface an *idle* expiry — zero
    bytes of the next frame read — as TimeoutError for the caller's
    heartbeat logic to judge; a deadline that expires MID-frame is a
    broken peer, not idleness, and raises WireError (resuming a
    half-read frame is impossible — the stream position is lost)."""
    payload = recv_frame(sock)
    if payload is None:
        return None
    msg = parse_payload(payload, allow_binary=allow_binary)
    # The receive-side twin of send_frame's mark: frame size + decoded
    # kind, so a merged timeline shows each hop's traffic inline.
    tracing.event("wire.recv", "wire", bytes=len(payload), t=msg.get("t"))
    return msg


def recv_frame(sock: socket.socket) -> Optional[bytes]:
    """Next RAW frame payload (length prefix stripped, nothing
    parsed), or None on clean EOF at a frame boundary — the relay
    tier's read primitive: a relay forwards these bytes verbatim
    downstream (zero re-encode) and parses its own copy separately.
    Deadline semantics are exactly recv_msg's (idle expiry →
    TimeoutError, mid-frame → WireError)."""
    header = _recv_exact(sock, _LEN.size, allow_eof=True)
    if header is None:
        return None
    (n,) = _LEN.unpack(header)
    if n > MAX_FRAME:
        raise WireError(f"frame too large: {n} bytes")
    try:
        return _recv_exact(sock, n, allow_eof=False)
    except TimeoutError:
        raise WireError(
            "receive deadline expired mid-frame (header without payload)"
        ) from None


def parse_payload(payload: bytes, allow_binary: bool = True) -> dict:
    """One raw frame payload -> the message dict (JSON or parsed
    binary frame) — recv_msg's decode half, shared with consumers
    that keep the raw bytes (the relay)."""
    if payload[:1] == b"{":
        try:
            return json.loads(payload.decode())
        except (ValueError, UnicodeDecodeError) as e:
            raise WireError(f"malformed JSON frame: {e}") from None
    if not allow_binary:
        raise WireError("unexpected binary frame on a control-only link")
    return _parse_frame(payload)


def _recv_exact(sock: socket.socket, n: int, allow_eof: bool) -> Optional[bytes]:
    """THE raw-socket read primitive of the wire plane (the
    blocking-io-timeout analysis check pins that: every other read in
    gol_tpu/distributed goes through recv_msg, whose sockets carry a
    deadline). A read deadline expiring with zero bytes buffered is
    clean idleness and propagates as TimeoutError; expiring mid-frame
    means the stream position is lost and raises WireError."""
    buf = bytearray()
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except TimeoutError:
            if not buf:
                raise
            raise WireError("receive deadline expired mid-frame") from None
        if not chunk:
            if allow_eof and not buf:
                return None
            raise WireError("connection closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)


# --- binary frames (negotiated via hello "binary") ---

#: Frame tags (first payload byte). JSON payloads start with '{'
#: (0x7b), so any tag < 0x20 is unambiguous.
_TAG_FLIPS, _TAG_BOARD, _TAG_FINAL, _TAG_LFLIPS, _TAG_HB = 1, 2, 3, 4, 5
_TAG_DFLIPS = 6
_TAG_FBATCH = 7
_TAG_MSAMPLES = 8
_FLIPS_HDR = struct.Struct("<BQ")       # tag, turn
_BOARD_HDR = struct.Struct("<BQIIQ")    # tag, turn, width, height, token
_FINAL_HDR = struct.Struct("<BQ")       # tag, turn
_LFLIPS_HDR = struct.Struct("<BQI")     # tag, turn, coords-blob bytes
_HB_HDR = struct.Struct("<BQ")          # tag, turn (liveness beacon)
_DFLIPS_HDR = struct.Struct("<BQII")    # tag, turn, changed words, bitmap-blob bytes
#: tag, first turn, k (turns), nb (bitmap words/turn), emit ts, then
#: the three blob lengths: per-turn delta counts, delta bitmaps (one
#: row per nonzero-count turn), delta word masks (Σcounts values).
_FBATCH_HDR = struct.Struct("<BQIIdIII")
#: Turns one batch frame may claim — far above any negotiable max-k
#: (the engine's diff-chunk budget caps real batches in the hundreds
#: to low thousands); a header claiming more is an attack, not a peer.
FBATCH_MAX_TURNS = 1 << 16


def _coords_to_frame(hdr: struct.Struct, tag: int, turn: int,
                     cells) -> bytes:
    """The one coordinate-list encoding (header + zlib'd int32 x,y
    pairs) behind both the flips and final frames — the encode twin of
    `_coords_from`."""
    coords = np.ascontiguousarray(np.asarray(cells, np.int32).reshape(-1, 2))
    return hdr.pack(tag, turn) + zlib.compress(coords.tobytes(), 1)


def flips_to_frame(turn: int, cells) -> bytes:
    """One turn's flip batch as a raw binary frame — the compact JSON
    form minus its ~33% base64 inflation on a link-bound path."""
    return _coords_to_frame(_FLIPS_HDR, _TAG_FLIPS, turn, cells)


def board_to_frame(turn: int, world: np.ndarray, token: int = 0) -> bytes:
    h, w = world.shape
    raw = zlib.compress(np.ascontiguousarray(world, np.uint8).tobytes(), 1)
    return _BOARD_HDR.pack(_TAG_BOARD, turn, w, h, token) + raw


def final_to_frame(turn: int, alive) -> bytes:
    return _coords_to_frame(_FINAL_HDR, _TAG_FINAL, turn, alive)


def level_flips_to_frame(turn: int, cells, levels) -> bytes:
    """A multi-state turn's flips WITH their new gray levels (r5 gens
    visualisation): coords blob + levels blob, both zlib'd."""
    coords = np.ascontiguousarray(np.asarray(cells, np.int32).reshape(-1, 2))
    lv = np.ascontiguousarray(np.asarray(levels, np.uint8).reshape(-1))
    if len(lv) != len(coords):
        raise ValueError(f"{len(coords)} cells vs {len(lv)} levels")
    cz = zlib.compress(coords.tobytes(), 1)
    return (_LFLIPS_HDR.pack(_TAG_LFLIPS, turn, len(cz))
            + cz + zlib.compress(lv.tobytes(), 1))


def grid_words(width: int, height: int) -> tuple[int, int]:
    """(total packed words, bitmap words) of the wire-level changed-word
    grid for a WxH board: 32 vertically-adjacent cells per word, words
    numbered (y//32)*width + x — a wire-layer convention shared by both
    endpoints, independent of how (or whether) the device packs."""
    total = -(-height // 32) * width
    return total, -(-total // 32)


def coords_to_words(cells, width: int, height: int):
    """One turn's flip coords -> (bitmap, words): the changed-word
    bitmap (grid_words' second element long) and the changed words' XOR
    masks in ascending word order — the delta-of-sparse frame's payload
    (the server-side encode twin of `words_to_coords`)."""
    xy = np.ascontiguousarray(np.asarray(cells, np.int64).reshape(-1, 2))
    total, nb = grid_words(width, height)
    flat = (xy[:, 1] // 32) * width + xy[:, 0]
    bit = np.uint32(1) << (xy[:, 1] % 32).astype(np.uint32)
    uniq, inv = np.unique(flat, return_inverse=True)
    words = np.zeros(len(uniq), np.uint32)
    np.bitwise_or.at(words, inv, bit)
    bitmap = np.zeros(nb, np.uint32)
    np.bitwise_or.at(
        bitmap, (uniq >> 5).astype(np.int64),
        np.uint32(1) << (uniq & 31).astype(np.uint32),
    )
    return bitmap, words


def words_to_coords(bitmap, words, width: int, height: int) -> np.ndarray:
    """(bitmap, words) -> (N, 2) int32 x,y flip coords in row-major
    (y, x) order — the SAME order the coord-frame path delivers, so the
    downstream event stream is identical either way. Raises WireError
    on any inconsistency: bitmap popcount vs word count, set bits
    outside the grid, or mask bits past the board height (the last
    word of a non-multiple-of-32 board)."""
    total, nb = grid_words(width, height)
    bitmap = np.asarray(bitmap, np.uint32)
    words = np.asarray(words, np.uint32)
    shifts = np.arange(32, dtype=np.uint32)
    idx = np.flatnonzero((bitmap[:, None] >> shifts) & 1)
    if idx.size != len(words):
        raise WireError(
            f"delta-flips bitmap pops {idx.size} words, frame carries "
            f"{len(words)}"
        )
    if idx.size and int(idx.max()) >= total:
        raise WireError("delta-flips bitmap bit outside the board grid")
    rows, bits = np.nonzero(((words[:, None] >> shifts) & 1).astype(bool))
    x = idx[rows] % width
    y = (idx[rows] // width) * 32 + bits
    if y.size and int(y.max()) >= height:
        raise WireError("delta-flips mask bit past the board height")
    order = np.lexsort((x, y))
    return np.column_stack([x[order], y[order]]).astype(np.int32)


def delta_flips_to_frame(turn: int, bitmap_delta, words) -> bytes:
    """One turn's flips as a delta-of-sparse binary frame: the
    changed-word bitmap XORed against the previous SENT turn's bitmap,
    plus the changed words' XOR masks (see the module docstring)."""
    bz = zlib.compress(
        np.ascontiguousarray(bitmap_delta, np.uint32).tobytes(), 1
    )
    wz = zlib.compress(np.ascontiguousarray(words, np.uint32).tobytes(), 1)
    return (_DFLIPS_HDR.pack(_TAG_DFLIPS, turn, len(words), len(bz))
            + bz + wz)


def heartbeat_to_frame(turn: int) -> bytes:
    """The server's liveness beacon as a raw binary frame (9 bytes on
    the wire) — carries the committed turn so an idle-attached client
    can still show progress. JSON peers get `{"t":"hb","turn":N}`."""
    return _HB_HDR.pack(_TAG_HB, turn)


# --- remote-write metric samples (the history plane) ---

#: tag, emit wall ts (epoch seconds), sample count, flags — then one
#: zlib blob: JSON `{"s": [[key, value], ...], "m": {...}}`. Samples
#: carry ABSOLUTE values of series that CHANGED since the sender's
#: previous push ("delta-encoded" means delta in the series *set*,
#: never in the values, so a lost frame can only delay a point — it
#: can never corrupt later ones); a frame with MSAMPLES_FULL set
#: carries the sender's whole registry (sent on (re)connect, and on a
#: keyframe cadence, so the collector can seed segment keyframes).
_MSAMPLES_HDR = struct.Struct("<BdII")
MSAMPLES_FULL = 1
#: Samples one frame may claim — a sidecar registry tops out in the
#: hundreds of series; a header claiming more is an attack, not a peer.
MSAMPLES_MAX = 1 << 16
#: Longest series key (`name{labels}`) a sample may carry. Bounds the
#: decompression allowance computed from the header's sample count, so
#: a lying header cannot buy itself a big inflation budget.
MSAMPLE_KEY_MAX = 512
#: Allowance for the optional meta dict (alert state transitions and
#: span digests ride along with the samples).
MSAMPLES_META_MAX = 64 << 10


def samples_to_frame(ts: float, samples, *, full: bool = False,
                     meta: Optional[dict] = None) -> bytes:
    """Assemble one _TAG_MSAMPLES frame from (key, value) pairs."""
    obj = {"s": [[k, float(v)] for k, v in samples]}
    if meta:
        obj["m"] = meta
    raw = json.dumps(obj, separators=(",", ":")).encode()
    return (_MSAMPLES_HDR.pack(_TAG_MSAMPLES, ts, len(obj["s"]),
                               MSAMPLES_FULL if full else 0)
            + zlib.compress(raw, 1))


def _parse_msamples(payload: bytes) -> dict:
    _, ts, n, flags = _MSAMPLES_HDR.unpack_from(payload)
    if n > MSAMPLES_MAX:
        raise WireError(f"implausible sample count {n}")
    if not math.isfinite(ts):
        raise WireError("non-finite samples timestamp")
    limit = 1024 + n * (MSAMPLE_KEY_MAX + 64) + MSAMPLES_META_MAX
    raw = _decompress(payload[_MSAMPLES_HDR.size:], limit=limit)
    try:
        obj = json.loads(raw.decode())
    except (ValueError, UnicodeDecodeError) as e:
        raise WireError(f"malformed samples payload: {e}") from None
    entries = obj.get("s") if isinstance(obj, dict) else None
    if not isinstance(entries, list):
        raise WireError("samples payload carries no sample list")
    if len(entries) != n:
        raise WireError(
            f"header says {n} samples, payload carries {len(entries)}"
        )
    samples = []
    for item in entries:
        if (not isinstance(item, list) or len(item) != 2
                or not isinstance(item[0], str)
                or not isinstance(item[1], (int, float))
                or isinstance(item[1], bool)):
            raise WireError("malformed sample entry")
        key, value = item[0], float(item[1])
        if len(key) > MSAMPLE_KEY_MAX:
            raise WireError(
                f"sample key of {len(key)} chars exceeds "
                f"{MSAMPLE_KEY_MAX}"
            )
        if not math.isfinite(value):
            raise WireError(f"non-finite sample value for {key!r}")
        samples.append((key, value))
    meta = obj.get("m", {})
    if not isinstance(meta, dict):
        raise WireError("samples meta is not an object")
    return {"t": "msamples", "ts": ts,
            "full": bool(flags & MSAMPLES_FULL),
            "samples": samples, "meta": meta}


# --- k-turn flip batches (negotiated via hello "batch") ---

#: Raw-payload ceiling under which a batch blob is worth deflating.
#: Measured on the serving container: zlib level 1 runs ~20 MB/s on
#: incompressible word masks — fine for the few-KB payloads a settled
#: board produces per batch, ruinous on the multi-MB payloads of an
#: active board (it would cost more wall time than the link saves on
#: loopback/LAN). Each blob carries a codec byte, so the choice is
#: per-blob and per-frame, never negotiated.
FBATCH_ZLIB_MAX = 64 << 10


def _pack_blob(raw: bytes) -> bytes:
    """codec byte (0 = raw, 1 = zlib) + payload."""
    if len(raw) <= FBATCH_ZLIB_MAX:
        z = zlib.compress(raw, 1)
        if len(z) < len(raw):
            return b"\x01" + z
    return b"\x00" + raw


def _unpack_blob(blob: bytes, limit: int) -> bytes:
    """Decode one codec-tagged batch blob with a hard output bound
    (the caller knows the exact expected size from the header)."""
    if not blob:
        raise WireError("empty batch blob")
    codec, data = blob[0], blob[1:]
    if codec == 0:
        if len(data) > limit:
            raise WireError(
                f"batch blob of {len(data)} bytes exceeds {limit}"
            )
        return data
    if codec == 1:
        return _decompress(data, limit=max(limit, 1))
    raise WireError(f"unknown batch blob codec {codec}")


def _bitmap_indices(bitmap_row) -> np.ndarray:
    """Set-bit positions of one changed-word bitmap row, ascending —
    the word indices its masks land at."""
    shifts = np.arange(32, dtype=np.uint32)
    return np.flatnonzero((bitmap_row[:, None] >> shifts) & 1)


def _indices_to_bitmap(idx, nb: int) -> np.ndarray:
    bm = np.zeros(nb, np.uint32)
    np.bitwise_or.at(
        bm, (idx >> 5).astype(np.int64),
        np.uint32(1) << (idx & 31).astype(np.uint32),
    )
    return bm


def chunk_deltas(counts, bitmaps, values, a: int, b: int,
                 total_words: int):
    """Turn-axis delta of one chunk segment: per-turn S-sparse rows
    (`counts` (k,), changed-word `bitmaps` (k, nb) uint32, `values`
    (Σcounts,) uint32 masks in ascending word order per turn — the
    device compact layout) for turns [a, b) become (dcounts,
    dbitmaps, dwords) where row i is D[i] = S[a+i] XOR S[a+i-1]
    (D[0] = S[a] raw: frames are self-contained). `dbitmaps` carries
    one row per NONZERO dcount, in turn order.

    The dominant case — a settled board, where S[t] == S[t-1] exactly
    — is detected by whole-array comparison (no per-word work); only
    genuinely differing adjacent turns pay a dense XOR of their two
    scattered rows."""
    counts = np.asarray(counts, np.int64)
    k = b - a
    offs = np.zeros(len(counts) + 1, np.int64)
    np.cumsum(counts, out=offs[1:])
    cnts = counts[a:b]
    bms = np.asarray(bitmaps, np.uint32)[a:b]
    same = np.zeros(k, bool)
    if k > 1:
        cand = (cnts[1:] == cnts[:-1]) & (bms[1:] == bms[:-1]).all(axis=1)
        if cand.any():
            if (cnts == cnts[0]).all() and cnts[0] > 0:
                # Uniform counts (the settled steady state): one
                # reshaped compare settles value equality for every
                # adjacent pair at once.
                v = values[offs[a]:offs[b]].reshape(k, int(cnts[0]))
                same[1:] = cand & (v[1:] == v[:-1]).all(axis=1)
            else:
                for t in (np.flatnonzero(cand) + 1):
                    lo, hi = offs[a + t], offs[a + t + 1]
                    plo, phi = offs[a + t - 1], offs[a + t]
                    same[t] = np.array_equal(values[lo:hi],
                                             values[plo:phi])
    dcounts = np.zeros(k, np.uint32)
    drows = []
    dparts = []
    for t in range(k):
        if t and same[t]:
            continue  # D[t] == 0
        lo, hi = offs[a + t], offs[a + t + 1]
        if t == 0:
            if cnts[0]:
                dcounts[0] = cnts[0]
                drows.append(bms[0])
                dparts.append(values[lo:hi])
            continue
        d = np.zeros(total_words, np.uint32)
        d[_bitmap_indices(bms[t])] = values[lo:hi]
        plo, phi = offs[a + t - 1], offs[a + t]
        d[_bitmap_indices(bms[t - 1])] ^= values[plo:phi]
        nz = np.flatnonzero(d)
        if nz.size:
            dcounts[t] = nz.size
            drows.append(_indices_to_bitmap(nz, bms.shape[1]))
            dparts.append(d[nz])
    nb = bms.shape[1]
    dbitmaps = (np.stack(drows) if drows
                else np.zeros((0, nb), np.uint32))
    dwords = (np.concatenate(dparts) if dparts
              else np.zeros(0, np.uint32))
    return dcounts, dbitmaps, dwords


def flip_batch_to_frame(first_turn: int, nb: int, dcounts, dbitmaps,
                        dwords, ts: float) -> bytes:
    """Assemble one _TAG_FBATCH frame from turn-axis deltas (the
    `chunk_deltas` output shape)."""
    dcounts = np.ascontiguousarray(dcounts, np.uint32)
    dbitmaps = np.ascontiguousarray(dbitmaps, np.uint32)
    dwords = np.ascontiguousarray(dwords, np.uint32)
    blobs = [_pack_blob(dcounts.tobytes()),
             _pack_blob(dbitmaps.tobytes()),
             _pack_blob(dwords.tobytes())]
    return _FBATCH_HDR.pack(
        _TAG_FBATCH, first_turn, len(dcounts), nb, ts,
        len(blobs[0]), len(blobs[1]), len(blobs[2]),
    ) + b"".join(blobs)


def _parse_fbatch(payload: bytes) -> dict:
    (_, first, k, nb, ts, lc, lb, lw) = _FBATCH_HDR.unpack_from(payload)
    if not 0 < k <= FBATCH_MAX_TURNS:
        raise WireError(f"implausible batch turn count {k}")
    if not 0 < nb <= MAX_RAW // 4:
        raise WireError(f"implausible batch bitmap width {nb}")
    body = payload[_FBATCH_HDR.size:]
    if lc + lb + lw != len(body):
        raise WireError("batch blobs disagree with the frame length")
    craw = _unpack_blob(body[:lc], 4 * k)
    if len(craw) != 4 * k:
        raise WireError(
            f"batch header says {k} turns, counts blob carries "
            f"{len(craw)} bytes"
        )
    counts = np.frombuffer(craw, np.uint32)
    nnz = int(np.count_nonzero(counts))
    total = int(counts.sum(dtype=np.int64))
    if total > MAX_RAW // 4 or nnz * nb > MAX_RAW // 4:
        raise WireError(f"implausible batch payload ({total} words)")
    braw = _unpack_blob(body[lc:lc + lb], 4 * nnz * nb)
    if len(braw) != 4 * nnz * nb:
        raise WireError(
            f"batch bitmap blob of {len(braw)} bytes, {nnz} nonzero "
            f"turns x {nb} words expected"
        )
    wraw = _unpack_blob(body[lc + lb:], 4 * total)
    if len(wraw) != 4 * total:
        raise WireError(
            f"batch counts sum to {total} words, mask blob carries "
            f"{len(wraw)} bytes"
        )
    dbitmaps = np.frombuffer(braw, np.uint32).reshape(nnz, nb)
    # Every nonzero turn's bitmap must pop exactly its count — a lying
    # count would misalign every later turn's mask slice.
    pops = np.bitwise_count(dbitmaps).sum(axis=1, dtype=np.int64)
    if not np.array_equal(pops, counts[counts > 0].astype(np.int64)):
        raise WireError("batch bitmap popcounts disagree with counts")
    return {"t": "fbatch", "first_turn": first, "k": k, "nb": nb,
            "ts": ts, "counts": counts, "dbitmaps": dbitmaps,
            "dwords": np.frombuffer(wraw, np.uint32)}


def _coords_from(blob: bytes) -> np.ndarray:
    raw = _decompress(blob)
    if len(raw) % 8:
        raise WireError(f"coordinate payload of {len(raw)} bytes")
    return np.frombuffer(raw, np.int32).reshape(-1, 2)


def _parse_frame(payload: bytes) -> dict:
    """Binary frame -> the dict shape its JSON sibling decodes to, with
    the payload already parsed ("coords" / "world" keys instead of the
    base64 fields). Every malformed-frame failure surfaces as
    WireError — struct/zlib/reshape errors escaping here would kill
    accept/reader threads whose handlers only expect WireError/OSError
    (a peer could wedge the server pre-auth with a 5-byte frame)."""
    try:
        return _parse_frame_inner(payload)
    except WireError:
        raise
    except (struct.error, zlib.error, ValueError, IndexError) as e:
        raise WireError(f"malformed binary frame: {e}") from None


def _parse_frame_inner(payload: bytes) -> dict:
    tag = payload[0]
    if tag == _TAG_FLIPS:
        _, turn = _FLIPS_HDR.unpack_from(payload)
        return {"t": "flips", "turn": turn,
                "coords": _coords_from(payload[_FLIPS_HDR.size:])}
    if tag == _TAG_BOARD:
        _, turn, w, h, token = _BOARD_HDR.unpack_from(payload)
        if h <= 0 or w <= 0 or h * w > MAX_RAW:
            raise WireError(f"implausible board dimensions {w}x{h}")
        raw = _decompress(payload[_BOARD_HDR.size:], limit=h * w)
        return {"t": "board", "turn": turn, "width": w, "height": h,
                "token": token,
                "world": np.frombuffer(raw, np.uint8).reshape(h, w)}
    if tag == _TAG_FINAL:
        _, turn = _FINAL_HDR.unpack_from(payload)
        return {"t": "ev", "k": "final", "turn": turn,
                "coords": _coords_from(payload[_FINAL_HDR.size:])}
    if tag == _TAG_LFLIPS:
        _, turn, czlen = _LFLIPS_HDR.unpack_from(payload)
        body = payload[_LFLIPS_HDR.size:]
        if czlen > len(body):
            raise WireError("level-flips coords blob overruns the frame")
        coords = _coords_from(body[:czlen])
        lv = np.frombuffer(_decompress(body[czlen:]), np.uint8)
        if len(lv) != len(coords):
            raise WireError(
                f"{len(coords)} cells vs {len(lv)} levels in frame"
            )
        return {"t": "flips", "turn": turn, "coords": coords, "levels": lv}
    if tag == _TAG_DFLIPS:
        _, turn, m, bzlen = _DFLIPS_HDR.unpack_from(payload)
        body = payload[_DFLIPS_HDR.size:]
        if bzlen > len(body):
            raise WireError("delta-flips bitmap blob overruns the frame")
        if m > MAX_RAW // 4:
            raise WireError(f"implausible delta-flips word count {m}")
        braw = _decompress(body[:bzlen])
        if len(braw) % 4:
            raise WireError(
                f"delta-flips bitmap payload of {len(braw)} bytes"
            )
        # The header states the exact word count — bound the value
        # inflation to it (a zero-word frame still needs a 1-byte
        # allowance: max_length=0 would mean UNLIMITED to zlib).
        wraw = _decompress(body[bzlen:], limit=max(4 * m, 1))
        if len(wraw) != 4 * m:
            raise WireError(
                f"delta-flips header says {m} words, payload carries "
                f"{len(wraw)} bytes"
            )
        return {"t": "dflips", "turn": turn,
                "dbitmap": np.frombuffer(braw, np.uint32),
                "dwords": np.frombuffer(wraw, np.uint32)}
    if tag == _TAG_FBATCH:
        return _parse_fbatch(payload)
    if tag == _TAG_MSAMPLES:
        return _parse_msamples(payload)
    if tag == _TAG_HB:
        _, turn = _HB_HDR.unpack_from(payload)
        return {"t": "hb", "turn": turn}
    # Unknown tags pass through as an ignorable kind (forward compat,
    # like unknown JSON "t" values).
    return {"t": f"bin{tag}"}


# --- event (de)serialization ---

_STATE = {s.name: s for s in State}


def event_to_msg(ev: Event) -> dict:
    if isinstance(ev, AliveCellsCount):
        return {"t": "ev", "k": "alive", "turn": ev.completed_turns,
                "count": ev.cells_count}
    if isinstance(ev, ImageOutputComplete):
        return {"t": "ev", "k": "image", "turn": ev.completed_turns,
                "filename": ev.filename}
    if isinstance(ev, StateChange):
        return {"t": "ev", "k": "state", "turn": ev.completed_turns,
                "state": ev.new_state.name}
    if isinstance(ev, TurnComplete):
        return {"t": "ev", "k": "turn", "turn": ev.completed_turns}
    if isinstance(ev, FinalTurnComplete):
        # The alive set can be millions of cells (a 5120^2 board at 25%
        # density is ~6.5M) — plain JSON pairs would blow MAX_FRAME, so
        # the coordinates ride as zlib(int32 x,y pairs) like board rasters.
        # Cell is a NamedTuple, so asarray builds the (N, 2) x,y array
        # directly — no per-cell intermediate lists on multi-million-cell
        # finals.
        coords = np.asarray(ev.alive, np.int32).reshape(-1, 2)
        packed = base64.b64encode(zlib.compress(coords.tobytes(), 1))
        return {"t": "ev", "k": "final", "turn": ev.completed_turns,
                "alive_z": packed.decode("ascii")}
    if isinstance(ev, CellFlipped):  # normally batched into "flips";
        # single-cell form stays legacy JSON (decodable by every peer)
        return {"t": "flips", "turn": ev.completed_turns,
                "cells": [[ev.cell.x, ev.cell.y]]}
    raise TypeError(f"unserializable event {ev!r}")


def msg_flips_array(msg: dict) -> tuple:
    """(turn, (N, 2) int32 x,y array) from a flips message — the
    vectorized decode (Controller batch mode); `msg_to_events` expands
    the same array into per-cell CellFlipped events."""
    turn = msg["turn"]
    if "coords" in msg:  # binary frame, already parsed
        coords = msg["coords"]
    elif "cells_z" in msg:
        coords = np.frombuffer(
            _decompress(base64.b64decode(msg["cells_z"])), np.int32
        ).reshape(-1, 2)
    else:
        coords = np.asarray(msg["cells"], np.int32).reshape(-1, 2)
    return turn, coords


def flips_to_msg(turn: int, cells, levels=None) -> dict:
    """One turn's flip batch as zlib'd int32 (x, y) pairs — the board-
    raster/FinalTurnComplete treatment applied to the per-turn stream
    (VERDICT r3 Weak #6). An active 512² board flips ~10³-10⁴ cells per
    turn; JSON pairs cost ~9 bytes/cell on the wire, this ~1-2.
    `levels` (multi-state rules) rides alongside as zlib'd bytes."""
    coords = np.asarray(cells, np.int32).reshape(-1, 2)
    packed = base64.b64encode(zlib.compress(coords.tobytes(), 1))
    msg = {"t": "flips", "turn": turn, "cells_z": packed.decode("ascii")}
    if levels is not None:
        lv = np.ascontiguousarray(np.asarray(levels, np.uint8).reshape(-1))
        if len(lv) != len(coords):
            raise ValueError(f"{len(coords)} cells vs {len(lv)} levels")
        msg["levels_z"] = base64.b64encode(
            zlib.compress(lv.tobytes(), 1)
        ).decode("ascii")
    return msg


def msg_flips_levels(msg: dict):
    """The (N,) uint8 level array of a flips message, or None for a
    two-state batch. Length agreement with the coords is checked at
    decode time for binary frames; JSON callers pair this with
    `msg_flips_array` and verify themselves."""
    if "levels" in msg:  # binary frame, already parsed
        return msg["levels"]
    if "levels_z" in msg:
        return np.frombuffer(
            _decompress(base64.b64decode(msg["levels_z"])), np.uint8
        )
    return None


def msg_to_events(msg: dict) -> list[Event]:
    """Expand one engine→controller message into Event objects (a "flips"
    batch becomes one CellFlipped per cell)."""
    t = msg["t"]
    if t == "flips":
        turn, coords = msg_flips_array(msg)
        return [CellFlipped(turn, Cell(int(x), int(y))) for x, y in coords]
    if t != "ev":
        raise TypeError(f"not an event message: {msg!r}")
    k, turn = msg["k"], msg["turn"]
    if k == "alive":
        return [AliveCellsCount(turn, msg["count"])]
    if k == "image":
        return [ImageOutputComplete(turn, msg["filename"])]
    if k == "state":
        return [StateChange(turn, _STATE[msg["state"]])]
    if k == "turn":
        return [TurnComplete(turn)]
    if k == "final":
        if "coords" in msg:  # binary frame, already parsed
            coords = msg["coords"]
        else:
            coords = np.frombuffer(
                _decompress(base64.b64decode(msg["alive_z"])), np.int32
            ).reshape(-1, 2)
        return [FinalTurnComplete(turn, [Cell(int(x), int(y)) for x, y in coords])]
    raise TypeError(f"unknown event kind {k!r}")


def board_to_msg(turn: int, world: np.ndarray, token: int = 0) -> dict:
    h, w = world.shape
    raw = zlib.compress(np.ascontiguousarray(world, np.uint8).tobytes(), 1)
    return {"t": "board", "turn": turn, "width": w, "height": h,
            "token": token, "data": base64.b64encode(raw).decode("ascii")}


def msg_to_board(msg: dict) -> tuple[int, np.ndarray]:
    if "world" in msg:  # binary frame, already parsed (and bounded)
        return msg["turn"], msg["world"]
    h, w = int(msg["height"]), int(msg["width"])
    if h <= 0 or w <= 0 or h * w > MAX_RAW:
        raise WireError(f"implausible board dimensions {w}x{h}")
    # The header states the exact raster size — bound the inflation to
    # it (reshape would reject a short payload either way).
    raw = _decompress(base64.b64decode(msg["data"]), limit=h * w)
    world = np.frombuffer(raw, np.uint8).reshape(h, w)
    return msg["turn"], world
