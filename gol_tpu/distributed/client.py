"""Controller client — the local half of the distributed split.

Connects to an `EngineServer`, replays the attach-time board sync as an
initial CellFlipped burst (exactly how the engine announces a freshly
loaded world, ref: gol/distributor.go:72-80), then exposes the remote
event stream as a local `EventQueue` — so the visualiser loop, shadow
boards and tests all work unchanged against a remote engine. Keyboard
verbs go the other way with `send_key` (ref: sdl/loop.go:18-27).

Detach/reattach (ref: README.md:182): `send_key('q')` — the server acks
with "detached", the local stream closes, the remote engine keeps
evolving; a new Controller can attach later and board-sync.
"""

from __future__ import annotations

import contextlib
import socket
import threading
import time
from typing import Optional

import numpy as np

from gol_tpu import obs
from gol_tpu.distributed import wire
from gol_tpu.engine.distributor import EventQueue
from gol_tpu.events import CellFlipped, FlipBatch, TurnComplete
from gol_tpu.utils.cell import cells_from_mask, xy_from_mask


class _ClientMetrics:
    """Registry handles for the controller plane (gol_tpu.obs): one
    observation per wire message, host-side only. `turn_latency` is the
    END-TO-END signal — broadcaster-enqueue (the server's `ts` stamp on
    TurnComplete) to applied-on-this-client — the first cross-process
    latency the system can see. Same-host pairs share a clock; across
    hosts the number includes NTP skew (docs/OBSERVABILITY.md)."""

    def __init__(self):
        self.turn_latency = obs.histogram(
            "gol_tpu_client_turn_latency_seconds",
            "Server TurnComplete emit -> applied on this client",
        )
        self.apply_seconds = obs.histogram(
            "gol_tpu_client_apply_seconds",
            "Decode-and-apply seconds per server message",
        )
        self.messages = {
            t: obs.counter(
                "gol_tpu_client_messages_total",
                "Server messages handled by kind", {"kind": t},
            ) for t in ("board", "flips", "ev", "other")
        }


_METRICS = _ClientMetrics()


class ServerBusyError(ConnectionError):
    """The engine already has a controller attached."""


class UnauthorizedError(ConnectionError):
    """The engine requires a shared secret this controller lacks."""


class Controller:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 8030,
        *,
        want_flips: bool = True,
        timeout: float = 30.0,
        secret: "str | None" = None,
        batch: bool = False,
        binary: bool = True,
        levels: bool = False,
        observe: bool = False,
    ):
        #: batch=True delivers each turn's flips as ONE events.FlipBatch
        #: ndarray instead of per-cell CellFlipped objects — the form
        #: vectorized consumers (the visualiser) apply directly; at
        #: thousands of flips/turn the per-cell expansion alone caps a
        #: watched run at ~30 turns/s. Default stays per-cell (the
        #: reference event contract).
        self._batch = batch
        #: levels=True (multi-state rules, r5): board syncs replay as
        #: level-setting batches and flips messages carrying levels
        #: surface them on the FlipBatch — pair with a level-mode board.
        self._levels = levels
        self.events = EventQueue()
        #: Board state from the attach sync (None until it arrives).
        self.board: Optional[np.ndarray] = None
        #: Completed turns as of the attach sync.
        self.sync_turn: int = 0
        #: Set once the attach-time BoardSync has been applied.
        self.synced = threading.Event()
        self.detached = threading.Event()
        self._send_lock = threading.Lock()
        # The timeout covers the whole handshake (connect + hello + first
        # reply), not just the TCP connect — a wedged server must not
        # hang the constructor. Streaming afterwards is untimed. Any
        # handshake failure closes the socket and the event stream.
        self._sock = socket.create_connection((host, port), timeout=timeout)
        try:
            # "compact" advertises the zlib'd-int32 flips encoding and
            # "binary" the raw tag+header+zlib frames; a server that
            # predates either just ignores the field and sends what it
            # knows (decodable on every path — recv_msg dispatches on
            # the first payload byte). `binary=False` pins the JSON
            # encodings (tests exercise the negotiation both ways).
            hello = {"t": "hello", "want_flips": want_flips,
                     "compact": True, "binary": bool(binary),
                     "levels": bool(levels)}
            if observe:
                # Read-only attach (r5 multi-observer serving): the
                # driver slot stays free, steering verbs are rejected
                # by the server; 'q' still detaches this observer.
                hello["role"] = "observe"
            if secret is not None:
                hello["secret"] = secret
            wire.send_msg(self._sock, hello)
            first = wire.recv_msg(self._sock)
        except (TimeoutError, wire.WireError, OSError) as e:
            self.close()
            raise ConnectionError(
                f"handshake with {host}:{port} failed: {e}"
            ) from None
        self._sock.settimeout(None)
        if first is not None and first.get("t") == "error":
            self.close()
            reason = first.get("reason", "rejected")
            if reason == "unauthorized":
                raise UnauthorizedError(reason)
            raise ServerBusyError(reason)
        self._reader = threading.Thread(
            target=self._reader_loop, args=(first,), name="gol-ctl-reader",
            daemon=True,
        )
        self._reader.start()

    def send_key(self, key: str) -> None:
        """Forward a keyboard verb (p/s/q/k) to the engine. Callable from
        any thread (stdin pump + visualiser share one controller)."""
        if key not in ("p", "s", "q", "k"):
            raise ValueError(f"unknown verb {key!r}")
        with self._send_lock:
            wire.send_msg(self._sock, {"t": "key", "key": key})

    def wait_sync(self, timeout: float = 60.0) -> bool:
        """Block until the attach-time board sync has been applied (or
        the stream closed first — returns False then)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.synced.wait(0.05):
                return True
            if self.events.closed:
                return self.synced.is_set()
        return self.synced.is_set()

    def detach(self, timeout: float = 30.0) -> bool:
        """'q': detach from the engine, leaving it running."""
        with contextlib.suppress(OSError, wire.WireError):
            self.send_key("q")
        return self.detached.wait(timeout)

    def close(self) -> None:
        with contextlib.suppress(OSError):
            self._sock.shutdown(socket.SHUT_RDWR)
        with contextlib.suppress(OSError):
            self._sock.close()
        self.events.close()

    # --- reader ---

    def _handle(self, msg: dict) -> bool:
        """Apply one server message; False ends the stream (metrics:
        one counter + one apply-seconds observation per message, and
        the emit→apply lag for stamped TurnCompletes)."""
        t0 = time.perf_counter()
        try:
            return self._handle_inner(msg)
        finally:
            t = msg.get("t")
            _METRICS.messages.get(t, _METRICS.messages["other"]).inc()
            _METRICS.apply_seconds.observe(time.perf_counter() - t0)
            if t == "ev" and msg.get("k") == "turn" and "ts" in msg:
                # Clamped at 0: a sub-millisecond negative reading is
                # clock granularity, not time travel.
                _METRICS.turn_latency.observe(
                    max(0.0, time.time() - float(msg["ts"]))
                )

    def _handle_inner(self, msg: dict) -> bool:
        t = msg.get("t")
        if t == "board":
            self.sync_turn, board = wire.msg_to_board(msg)
            # Replay as a flip burst + a render tick so any attached
            # visualiser shows the synced board immediately. Flips are
            # XOR for consumers, so the burst is the *difference* from
            # the previous known state — idempotent under repeated
            # syncs. Level mode compares gray grids directly and SETS
            # the changed cells' levels instead (no rule needed: the
            # raster IS the level grid).
            prev = self.board
            if self._levels:
                diff = board != (np.zeros_like(board) if prev is None else prev)
                self.board = board
                self.events.put(FlipBatch(
                    self.sync_turn, xy_from_mask(diff), levels=board[diff]
                ))
            else:
                diff = (board != 0 if prev is None
                        else (board != 0) ^ (prev != 0))
                self.board = board
                if self._batch:
                    self.events.put(
                        FlipBatch(self.sync_turn, xy_from_mask(diff))
                    )
                else:
                    for cell in cells_from_mask(diff):
                        self.events.put(CellFlipped(self.sync_turn, cell))
            self.events.put(TurnComplete(self.sync_turn))
            self.synced.set()
            return True
        if t == "flips" and self._batch:
            turn, coords = wire.msg_flips_array(msg)
            lv = wire.msg_flips_levels(msg) if self._levels else None
            if lv is not None and len(lv) != len(coords):
                raise wire.WireError(
                    f"{len(coords)} cells vs {len(lv)} levels"
                )
            self.events.put(FlipBatch(turn, coords, levels=lv))
            return True
        if t in ("ev", "flips"):
            for ev in wire.msg_to_events(msg):
                self.events.put(ev)
            return True
        if t == "detached":
            self.detached.set()
            return False
        if t == "bye":
            return False
        return True  # unknown message kinds are ignored (forward compat)

    def _reader_loop(self, first: Optional[dict]) -> None:
        try:
            msg = first
            while msg is not None and self._handle(msg):
                msg = wire.recv_msg(self._sock)
        except (wire.WireError, OSError):
            pass  # server died — surface as stream close
        finally:
            self.close()
