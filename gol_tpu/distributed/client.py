"""Controller client — the local half of the distributed split.

Connects to an `EngineServer`, replays the attach-time board sync as an
initial CellFlipped burst (exactly how the engine announces a freshly
loaded world, ref: gol/distributor.go:72-80), then exposes the remote
event stream as a local `EventQueue` — so the visualiser loop, shadow
boards and tests all work unchanged against a remote engine. Keyboard
verbs go the other way with `send_key` (ref: sdl/loop.go:18-27).

Detach/reattach (ref: README.md:182): `send_key('q')` — the server acks
with "detached", the local stream closes, the remote engine keeps
evolving; a new Controller can attach later and board-sync.

Resilience (docs/RESILIENCE.md): the reader is SUPERVISED. On a socket
failure — reset, EOF without a goodbye, or a missed heartbeat deadline
— it re-dials with exponential backoff + deterministic jitter, repeats
the handshake, and resumes through the ordinary BoardSync catch-up: the
client tracks the board it has handed downstream (applying each flip
batch to its shadow raster), so the reattach sync's XOR diff is exactly
the correction between what consumers have and where the engine is —
missed flips are never replayed, present ones never doubled, and
`synced_turn` gating drops any flip the synced board already contains.
When reconnection is disabled or exhausted the client parts with an
explicit `ConnectionLost` state (`lost` event, `state == "lost"`)
rather than an indistinguishable closed stream.

Observability (docs/OBSERVABILITY.md): the attach handshake runs a
clock probe against servers that advertise it — the min-RTT offset
sample corrects the emit→apply turn-latency histogram onto the
server's timebase, is exported as gol_tpu_client_clock_offset_seconds,
and rides the tracer's dump metadata so `gol_tpu.obs.report merge` can
join this side's spans with the server's on one timeline. Link
lifecycle (link_down / reconnected / board_sync / lost) lands on the
same timeline and in the flight recorder; reconnect exhaustion dumps
the black box.
"""

from __future__ import annotations

import contextlib
import logging
import random
import socket
import threading
import time
import uuid
from typing import Optional

import numpy as np

from gol_tpu import obs
from gol_tpu.distributed import wire
from gol_tpu.obs import flight, tracing
from gol_tpu.obs.freshness import ClientFreshness, sane_lag
from gol_tpu.engine.distributor import EventQueue
from gol_tpu.events import CellFlipped, FlipBatch, TurnComplete
from gol_tpu.utils.cell import Cell, cells_from_mask, xy_from_mask
from gol_tpu.analysis.concurrency import lockcheck

log = logging.getLogger(__name__)


class _ClientMetrics:
    """Registry handles for the controller plane (gol_tpu.obs): one
    observation per wire message, host-side only. `turn_latency` is the
    END-TO-END signal — broadcaster-enqueue (the server's `ts` stamp on
    TurnComplete) to applied-on-this-client — the first cross-process
    latency the system can see. Same-host pairs share a clock; across
    hosts the number includes NTP skew (docs/OBSERVABILITY.md)."""

    def __init__(self):
        self.turn_latency = obs.histogram(
            "gol_tpu_client_turn_latency_seconds",
            "Server TurnComplete emit -> applied on this client",
        )
        self.apply_seconds = obs.histogram(
            "gol_tpu_client_apply_seconds",
            "Decode-and-apply seconds per server message",
        )
        self.batch_latency = obs.histogram(
            "gol_tpu_client_batch_latency_seconds",
            "Batch-frame emit on the server -> whole k-turn batch "
            "applied here (PER-BATCH stamping, deliberately not fed "
            "into turn_latency — docs/OBSERVABILITY.md \"Batch "
            "latency semantics\")",
        )
        self.messages = {
            t: obs.counter(
                "gol_tpu_client_messages_total",
                "Server messages handled by kind", {"kind": t},
            ) for t in ("board", "flips", "dflips", "fbatch", "ev",
                        "other")
        }
        self.reconnects = obs.counter(
            "gol_tpu_client_reconnects_total",
            "Successful re-dial + re-handshake + resync cycles",
        )
        self.hb_miss = obs.counter(
            "gol_tpu_client_heartbeat_miss_total",
            "Read deadlines expired without a frame (liveness misses)",
        )
        self.lost = obs.counter(
            "gol_tpu_client_connection_lost_total",
            "Links declared permanently lost (reconnect off/exhausted)",
        )
        self.clock_offset = obs.gauge(
            "gol_tpu_client_clock_offset_seconds",
            "Handshake-estimated wall-clock offset to the server "
            "(server_time - client_time; min-RTT probe sample)",
        )
        self.turn_age = obs.gauge(
            "gol_tpu_client_turn_age_seconds",
            "Seconds this client's APPLIED turn lags the server's "
            "committed head (freshness plane: head learned from "
            "stamped events and heartbeat beacons on the corrected "
            "clock — what an observer actually experiences)",
        )


_METRICS = _ClientMetrics()


#: Ceiling on any server-supplied retry_after hint, seconds. A
#: malformed or hostile hint (negative, NaN, "a year") must never be
#: able to park a client forever — absurd values clamp into this range
#: and non-numeric ones are ignored (plain backoff applies).
RETRY_AFTER_CAP = 30.0


def sanitize_retry_after(value) -> "float | None":
    """The server's when-to-come-back hint, made safe to sleep on:
    a finite number clamped to [0, RETRY_AFTER_CAP], else None."""
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        return None
    v = float(value)
    if v != v or v in (float("inf"), float("-inf")):
        return None
    return min(max(v, 0.0), RETRY_AFTER_CAP)


class ServerBusyError(ConnectionError):
    """The engine already has a controller attached (or admission
    control shed this attach). `retry_after` carries the server's
    sanitized when-to-come-back hint in seconds, or None when the
    rejection had no (usable) hint."""

    def __init__(self, reason: str, retry_after: "float | None" = None):
        super().__init__(reason)
        self.retry_after = retry_after


class UnauthorizedError(ConnectionError):
    """The engine requires a shared secret this controller lacks."""


class UnknownSessionError(ConnectionError):
    """The named session does not exist on the session server."""


class ConnectionLost(ConnectionError):
    """The link died and reconnection was disabled or exhausted."""


class Controller:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 8030,
        *,
        want_flips: bool = True,
        timeout: float = 30.0,
        secret: "str | None" = None,
        batch: bool = False,
        batch_turns: "int | None" = None,
        batch_flip_events: bool = True,
        binary: bool = True,
        levels: bool = False,
        delta: bool = True,
        observe: bool = False,
        session: "str | None" = None,
        reconnect: bool = True,
        max_reconnects: Optional[int] = None,
        reconnect_window: float = 30.0,
        backoff_base: float = 0.05,
        backoff_cap: float = 2.0,
        reconnect_seed: Optional[int] = None,
    ):
        #: batch=True delivers each turn's flips as ONE events.FlipBatch
        #: ndarray instead of per-cell CellFlipped objects — the form
        #: vectorized consumers (the visualiser) apply directly; at
        #: thousands of flips/turn the per-cell expansion alone caps a
        #: watched run at ~30 turns/s. Default stays per-cell (the
        #: reference event contract).
        self._batch = batch
        #: batch_turns=k requests k-TURN WIRE FRAMES (hello "batch",
        #: r10): the server ships one _TAG_FBATCH frame per dispatch
        #: chunk instead of per-turn frames, and this client applies
        #: each frame with one vectorized XOR pass over the shadow
        #: raster — the ~300 -> 10⁵+ turns/s watched-path fix. The
        #: server clamps the request to its own --batch-turns cap;
        #: servers that predate the frame ignore the key and keep
        #: sending per-turn frames, which this client still handles.
        self._batch_turns = int(batch_turns) if batch_turns else 0
        #: With batch frames, per-turn FlipBatch/CellFlipped events
        #: are RECONSTRUCTED from the deltas (exact, but per-turn
        #: Python cost). batch_flip_events=False skips them — consumers
        #: read per-turn TurnComplete events plus the always-current
        #: `board` raster instead (the high-rate watching mode: a
        #: display renders from `board` at its own frame rate).
        self._batch_flip_events = batch_flip_events
        #: levels=True (multi-state rules, r5): board syncs replay as
        #: level-setting batches and flips messages carrying levels
        #: surface them on the FlipBatch — pair with a level-mode board.
        self._levels = levels
        self.events = EventQueue()
        #: Board state as of the last flip handed downstream — starts
        #: as the attach sync's raster and tracks every applied batch,
        #: so a reattach sync can diff against what consumers actually
        #: have (None until the first sync arrives).
        self.board: Optional[np.ndarray] = None
        #: Completed turns as of the last board sync.
        self.sync_turn: int = 0
        #: Gate against double-apply: flips for turns <= this are
        #: already inside the synced board and are dropped (the client
        #: twin of the server's per-peer synced_turn gate).
        self.synced_turn: int = -1
        #: Set once the attach-time BoardSync has been applied.
        self.synced = threading.Event()
        self.detached = threading.Event()
        #: Set when the link is PERMANENTLY gone (reconnect disabled,
        #: window/attempts exhausted, or a policy rejection on
        #: re-handshake) — the explicit state `wait_sync`/`detach`
        #: return against instead of silently timing out.
        self.lost = threading.Event()
        #: Successful reconnect cycles this controller has survived.
        self.reconnects = 0
        self._send_lock = lockcheck.make_lock("Controller._send_lock")
        self._closing = threading.Event()
        self._reconnecting = threading.Event()
        self._host, self._port = host, port
        self._timeout = timeout
        self._reconnect_enabled = reconnect
        self._max_reconnects = max_reconnects
        self._window = reconnect_window
        self._backoff_base = backoff_base
        self._backoff_cap = backoff_cap
        #: Deterministic jitter: a seeded PRNG makes a reconnect
        #: schedule replayable in tests (and across a fleet, seeds
        #: should differ so backed-off clients do not re-dial in
        #: lockstep).
        self._rng = random.Random(reconnect_seed)
        #: Heartbeat cadence the server confirmed in its attach-ack
        #: (0 = none negotiated; the read deadline stays unarmed).
        self._hb_secs = 0.0
        #: Clock-offset estimate to the server (seconds; server_time ≈
        #: client_time + offset), measured by the handshake ping/pong
        #: probe when the server advertises "clock" in its attach-ack.
        #: None until a probe run completes (legacy servers never echo,
        #: so it simply stays None and the latency math falls back to
        #: the raw cross-host subtraction, as before).
        self.clock_offset: Optional[float] = None
        self._clk_samples: "list[tuple[float, float]]" = []
        self._clk_left = 0
        self._clk_last_send = 0.0
        #: Delta-of-sparse flips chain state (r6): the changed-word
        #: bitmap of the last applied delta frame, reset at every
        #: board sync (the server resets its twin when it sends one).
        self._delta_prev: Optional[np.ndarray] = None
        #: Freshness plane (gol_tpu.obs.freshness): applied-turn age
        #: against the server's committed head — the head clock learns
        #: from stamped turn events/batch frames (emit stamps mapped
        #: onto the local clock via the PR 5 offset) and heartbeat
        #: beacons; `turn_age()` is the live reading the canary
        #: publishes.
        self.freshness = ClientFreshness()
        hello = {"t": "hello", "want_flips": want_flips,
                 "compact": True, "binary": bool(binary),
                 "levels": bool(levels), "hb": True, "clock": True,
                 # Delta frames carry no levels, so level mode keeps
                 # the LFLIPS encoding (negotiated OFF here).
                 "delta": bool(delta) and bool(binary) and not levels}
        if self._batch_turns > 0 and binary and not levels and want_flips:
            # k-turn batch frames (binary-only, two-state only — the
            # same constraints as delta frames — and only when flips
            # are actually subscribed: the server ignores a flip-less
            # "batch" anyway, so don't even advertise it).
            hello["batch"] = self._batch_turns
        if observe:
            # Read-only attach (r5 multi-observer serving): the
            # driver slot stays free, steering verbs are rejected
            # by the server; 'q' still detaches this observer.
            hello["role"] = "observe"
        if session is not None:
            # Multi-tenant attach (gol_tpu.sessions): watch/drive the
            # NAMED session on a `--serve --sessions` server. The rest
            # of the protocol — board sync, flips, reconnect-and-resync
            # — is unchanged; a reconnect re-handshakes with the same
            # session id, so supervision composes. (A pre-sessions
            # server ignores the unknown key and serves its singleton.)
            hello["session"] = session
        self.session = session
        if secret is not None:
            hello["secret"] = secret
        self._hello = hello
        #: Seek verb state (gol_tpu.replay, docs/REPLAY.md): the last
        #: `seek-r` reply and its arrival event — one outstanding seek
        #: at a time (the verb is a user-interaction, not a stream).
        self._seek_reply: Optional[dict] = None
        self._seek_done = threading.Event()
        self._seek_lock = lockcheck.make_lock("Controller._seek_lock")
        self._rid_n = 0
        self._rid_prefix = uuid.uuid4().hex[:12]
        self._sock, first = self._dial()
        self._arm_read_deadline()
        self._reader = threading.Thread(
            target=self._reader_loop, args=(first,), name="gol-ctl-reader",
            daemon=True,
        )
        self._reader.start()

    # --- link lifecycle ---

    def _dial(self) -> "tuple[socket.socket, Optional[dict]]":
        """One connect + handshake: returns the live socket and the
        server's first reply (normally the attach-ack, whose hb_secs
        arms the liveness deadline). Raises Unauthorized/ServerBusy on
        policy rejections, ConnectionError on everything else. The
        `timeout` covers the whole handshake — a wedged server must
        not hang the caller; streaming afterwards runs under the
        heartbeat deadline instead (see _arm_read_deadline)."""
        from gol_tpu.testing import faults

        sock = faults.wrap("client", socket.create_connection(
            (self._host, self._port), timeout=self._timeout
        ))
        # The handshake deadline (already set by create_connection;
        # re-applied on the wrapper so the discipline is explicit) —
        # replaced by the heartbeat deadline once the caller installs
        # the socket and calls _arm_read_deadline.
        sock.settimeout(self._timeout)
        try:
            wire.send_msg(sock, self._hello)
            first = wire.recv_msg(sock)
        except (TimeoutError, wire.WireError, OSError) as e:
            with contextlib.suppress(OSError):
                sock.close()
            raise ConnectionError(
                f"handshake with {self._host}:{self._port} failed: {e}"
            ) from None
        if first is not None and first.get("t") == "error":
            with contextlib.suppress(OSError):
                sock.close()
            reason = first.get("reason", "rejected")
            if reason == "unauthorized":
                raise UnauthorizedError(reason)
            if reason == "unknown-session":
                raise UnknownSessionError(reason)
            # Load rejections ("busy", "at-capacity") carry the
            # server's retry_after hint — sanitized here once, so
            # every consumer sleeps on a bounded number or not at all.
            raise ServerBusyError(
                reason, sanitize_retry_after(first.get("retry_after"))
            )
        sock.settimeout(None)
        if first is not None and first.get("t") == "attach-ack":
            self._hb_secs = float(first.get("hb_secs", 0) or 0)
        return sock, first

    def _arm_read_deadline(self) -> None:
        """Three missed heartbeat intervals with zero frames = the
        server is gone (docs/RESILIENCE.md). Servers that negotiated
        no heartbeats keep the legacy unbounded read — evicting a
        healthy-but-quiet legacy server would be worse than blocking."""
        deadline = 3.0 * self._hb_secs if self._hb_secs > 0 else None
        self._sock.settimeout(deadline)

    @property
    def state(self) -> str:
        """One-word link state: connected / reconnecting / detached /
        lost / closed — `lost` is the ConnectionLost outcome callers
        used to have to infer from a timed-out False."""
        if self.lost.is_set():
            return "lost"
        if self.detached.is_set():
            return "detached"
        if self.events.closed or self._closing.is_set():
            return "closed"
        if self._reconnecting.is_set():
            return "reconnecting"
        return "connected"

    def send_key(self, key: str) -> None:
        """Forward a keyboard verb (p/s/q/k) to the engine. Callable from
        any thread (stdin pump + visualiser share one controller).
        Raises ConnectionLost once the link is permanently gone."""
        if key not in ("p", "s", "q", "k"):
            raise ValueError(f"unknown verb {key!r}")
        if self.lost.is_set():
            raise ConnectionLost(
                f"link to {self._host}:{self._port} is gone"
            )
        with self._send_lock:
            wire.send_msg(self._sock, {"t": "key", "key": key})

    def seek(self, turn, timeout: float = 30.0,
             rid: "str | None" = None) -> dict:
        """Time-travel (gol_tpu.replay, docs/REPLAY.md): ask a
        recording-backed server to rewind this stream to `turn` (an
        int, or the literal "live" to rejoin the present). The server
        answers with the nearest <= turn keyframe's BoardSync plus the
        recorded FBATCH suffix — both ride the ORDINARY apply path, so
        `self.board` simply becomes the historical raster — followed
        by the `seek-r` completion reply this method returns (ok +
        landed turn, or ok=False with a reason). The verb is
        idempotent under rid replay; pass `rid` to retry a specific
        attempt. Raises TimeoutError when no reply arrives in time."""
        if rid is None:
            self._rid_n += 1
            rid = f"{self._rid_prefix}-seek-{self._rid_n}"
        with self._seek_lock:
            self._seek_reply = None
            self._seek_done.clear()
            with self._send_lock:
                wire.send_msg(self._sock,
                              {"t": "seek", "turn": turn, "rid": rid})
            deadline = time.monotonic() + timeout
            while not self._seek_done.wait(0.05):
                if self.lost.is_set() or self.events.closed \
                        or time.monotonic() > deadline:
                    break
            reply = self._seek_reply
        if reply is None:
            raise TimeoutError("no seek-r reply from the server")
        return reply

    def turn_age(self) -> float:
        """Live applied-turn age in seconds (freshness plane): how far
        this client's applied board lags the server's committed head —
        0.0 while current (or before anything is known), growing in
        real time while behind a live stream. The canary publishes
        exactly this reading."""
        return self.freshness.age()

    def wait_sync(self, timeout: float = 60.0) -> bool:
        """Block until the attach-time board sync has been applied.
        Returns False IMMEDIATELY once the stream closed or the link
        was declared lost — never waits out the timeout against a dead
        connection (check `state` to tell "lost" from "run over")."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.synced.wait(0.05):
                return True
            if self.lost.is_set() or self.events.closed:
                return self.synced.is_set()
        return self.synced.is_set()

    def detach(self, timeout: float = 30.0) -> bool:
        """'q': detach from the engine, leaving it running. Returns
        immediately (False) when the link is already dead instead of
        sleeping out the timeout waiting for an ack that cannot come."""
        if self.lost.is_set() or self.events.closed:
            return self.detached.is_set()
        try:
            self.send_key("q")
        except (OSError, ConnectionError):
            return self.detached.is_set()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.detached.wait(0.05):
                return True
            if self.lost.is_set() or self.events.closed:
                return self.detached.is_set()
        return self.detached.is_set()

    def close(self) -> None:
        self._closing.set()
        with contextlib.suppress(OSError):
            self._sock.shutdown(socket.SHUT_RDWR)
        with contextlib.suppress(OSError):
            self._sock.close()
        self.events.close()

    # --- reader ---

    #: Clock probes per (re)attach: enough samples for the min-RTT
    #: filter to dodge a scheduling hiccup, few enough to finish within
    #: the first seconds of a session.
    CLOCK_PROBES = 8

    #: A probe whose echo is this stale gets re-sent (from the next
    #: inbound message) instead of stalling the run forever — one
    #: dropped echo must not leave clock_offset unmeasured all session.
    CLOCK_PROBE_RETRY_SECS = 2.0

    def _send_clk(self) -> None:
        """One clock probe: the server echoes t0 back with its own
        wall clock (queue-free), and the reply's RTT bounds the offset
        error. Failures are ignored — the link supervisor owns socket
        death, and an unmeasured offset just stays None."""
        self._clk_last_send = time.monotonic()
        with contextlib.suppress(OSError, ConnectionError, wire.WireError):
            with self._send_lock:
                wire.send_msg(self._sock, {"t": "clk", "t0": time.time()})

    def _handle(self, msg: dict) -> bool:
        """Apply one server message; False ends the stream (metrics:
        one counter + one apply-seconds observation per message, and
        the emit→apply lag for stamped TurnCompletes)."""
        t0 = time.perf_counter()
        wall0 = time.time()
        applied = False
        try:
            ret = self._handle_inner(msg)
            applied = True
            return ret
        finally:
            t = msg.get("t")
            dt = time.perf_counter() - t0
            _METRICS.messages.get(t, _METRICS.messages["other"]).inc()
            _METRICS.apply_seconds.observe(dt)
            tracing.add_span("client.apply", "client", wall0, dt,
                             {"t": t})
            if (self._clk_left > 0 and t != "clk"
                    and time.monotonic() - self._clk_last_send
                    > self.CLOCK_PROBE_RETRY_SECS):
                # A probe's echo went missing (dropped frame, or the
                # send itself failed silently): re-fire on the next
                # inbound traffic rather than stalling the run with
                # clock_offset forever unmeasured. Stream-idle links
                # retry off the heartbeat cadence at worst.
                self._send_clk()
            # Everything below requires `applied`: a message that
            # FAILED to apply (WireError out of the handler, which is
            # propagating right now — no `return` here, it would
            # swallow it) must not feed the latency histograms or the
            # MONOTONE freshness clocks — a rejected frame carrying a
            # plausible-but-absurd turn (< 2^62) would wedge turn_age
            # at 0 for the process lifetime, blinding the very canary
            # this plane exists for.
            if applied and t == "fbatch":
                # Per-BATCH latency: emit-of-batch (the frame's one ts
                # stamp) -> whole batch applied. A separate histogram
                # on purpose: feeding per-batch readings into the
                # per-turn series would silently change its semantics
                # under bench_compare.
                # The emit stamp crossed the wire: sane_lag is the ONE
                # validation before it reaches a histogram — a
                # hostile/absurd ts (negative epoch, 1e18, NaN) is
                # dropped, never observed (the relay hop's rule,
                # applied at the leaf too; wire-fuzz-pinned).
                off = self.clock_offset or 0.0
                lag = sane_lag(msg.get("ts"), time.time() + off)
                if lag is not None:
                    _METRICS.batch_latency.observe(lag)
                # Binary frames guarantee these fields (parse-time
                # validation); a hostile JSON "fbatch" does not, and a
                # KeyError out of this finally block kills the reader.
                try:
                    last = int(msg["first_turn"]) + int(msg["k"]) - 1
                except (KeyError, TypeError, ValueError):
                    last = -1  # dropped by the sane_turn guards below
                # Freshness: the frame's last turn was committed at
                # ~(now - lag) on the LOCAL clock, and this apply just
                # caught the client up to it.
                self.freshness.note_head(
                    last, None if lag is None else time.time() - lag
                )
                self.freshness.note_applied(last)
                _METRICS.turn_age.set(round(self.freshness.age(), 6))
                tracing.event(
                    "turn.apply", "wire", turn=last,
                    batch=msg.get("k"),
                    lag_s=None if lag is None else round(lag, 6),
                )
            if applied and t == "hb":
                # Beacons carry the committed head turn precisely so
                # an idle or lagging client can still measure its own
                # staleness — the head clock advances, the applied
                # turn does not, and the age gauge tells the truth.
                self.freshness.note_head(msg.get("turn"))
                _METRICS.turn_age.set(round(self.freshness.age(), 6))
            if applied and t == "board":
                self.freshness.note_head(msg.get("turn"))
                self.freshness.note_applied(msg.get("turn"))
                _METRICS.turn_age.set(round(self.freshness.age(), 6))
            if applied and t == "ev" and msg.get("k") == "turn" \
                    and "ts" not in msg:
                # Legacy unstamped servers: the turn event itself is
                # the freshest head evidence there is.
                self.freshness.note_head(msg.get("turn"))
                self.freshness.note_applied(msg.get("turn"))
                _METRICS.turn_age.set(round(self.freshness.age(), 6))
            if applied and t == "ev" and msg.get("k") == "turn" \
                    and "ts" in msg:
                # The handshake-estimated offset moves this reading
                # onto the SERVER's timebase (server_now ≈ client_now
                # + offset); legacy servers leave the offset None and
                # the raw subtraction stands. sane_lag clamps sub-zero
                # readings (clock granularity, not time travel) and
                # DROPS hostile stamps — a JSON peer can put anything
                # in "ts", and "abc" used to raise out of this finally
                # block and kill the reader thread.
                off = self.clock_offset or 0.0
                lag = sane_lag(msg.get("ts"), time.time() + off)
                if lag is not None:
                    _METRICS.turn_latency.observe(lag)
                self.freshness.note_head(
                    msg.get("turn"),
                    None if lag is None else time.time() - lag,
                )
                self.freshness.note_applied(msg.get("turn"))
                _METRICS.turn_age.set(round(self.freshness.age(), 6))
                # The CLIENT half of the per-turn wire correlation
                # (pairs with the server's `turn.emit` in merged
                # timelines).
                tracing.event(
                    "turn.apply", "wire", turn=msg.get("turn"),
                    lag_s=None if lag is None else round(lag, 6),
                )

    def _handle_inner(self, msg: dict) -> bool:
        t = msg.get("t")
        if t == "attach-ack":
            # Start the clock-probe run on servers that echo probes
            # (negotiated via the ack's "clock"; re-measured after
            # every reconnect — the offset can drift with the peer).
            if msg.get("clock"):
                self._clk_samples = []
                self._clk_left = self.CLOCK_PROBES
                self._send_clk()
            return True
        if t == "clk":
            if self._clk_left <= 0:
                # Stray echo after the run finalized (a retry raced a
                # late original): the offset is published and latencies
                # were observed against it — never re-finalize or
                # duplicate the clock_sync lifecycle marks.
                return True
            t1 = time.time()
            try:
                pt0, ts = float(msg["t0"]), float(msg["ts"])
            except (KeyError, TypeError, ValueError):
                return True  # malformed echo: drop the sample
            rtt = max(0.0, t1 - pt0)
            # NTP-style midpoint estimate: the server stamped somewhere
            # inside [t0, t1]; assuming the midpoint bounds the error
            # by RTT/2, and keeping the MIN-RTT sample makes that bound
            # the tightest the link offered.
            self._clk_samples.append((rtt, ts - (pt0 + t1) / 2.0))
            self._clk_left -= 1
            if self._clk_left > 0:
                self._send_clk()
            else:
                rtt, off = min(self._clk_samples)
                if abs(off) <= rtt / 2.0:
                    # Zero lies inside the estimate's own error bound
                    # (±RTT/2): the clocks are indistinguishable from
                    # synchronized, and "correcting" by the residual
                    # would INJECT up to RTT/2 of noise — enough to
                    # reorder emit→apply pairs on a same-host run whose
                    # true latency is microseconds. Snap to the only
                    # value the measurement actually supports. Real
                    # cross-host skew (≫ RTT/2) always survives this.
                    off = 0.0
                self.clock_offset = off
                tracing.set_clock_offset(off)
                _METRICS.clock_offset.set(off)
                tracing.event("client.clock_sync", "lifecycle",
                              offset_s=round(off, 6),
                              rtt_s=round(rtt, 6))
                flight.note("client.clock_sync", offset_s=round(off, 6),
                            rtt_s=round(rtt, 6))
            return True
        if t == "board":
            self.sync_turn, board = wire.msg_to_board(msg)
            # Replay as a flip burst + a render tick so any attached
            # visualiser shows the synced board immediately. Flips are
            # XOR for consumers, so the burst is the *difference* from
            # the board as consumers currently have it (self.board
            # tracks every batch handed downstream) — which is what
            # makes a RECONNECT sync converge without replaying missed
            # flips or doubling delivered ones. Level mode compares
            # gray grids directly and SETS the changed cells' levels
            # instead (no rule needed: the raster IS the level grid).
            prev = self.board
            board = np.array(board, dtype=np.uint8)  # writable tracker
            if self._levels:
                diff = board != (np.zeros_like(board) if prev is None else prev)
                self.board = board
                self.events.put(FlipBatch(
                    self.sync_turn, xy_from_mask(diff), levels=board[diff]
                ))
            else:
                diff = (board != 0 if prev is None
                        else (board != 0) ^ (prev != 0))
                self.board = board
                if self._batch:
                    self.events.put(
                        FlipBatch(self.sync_turn, xy_from_mask(diff))
                    )
                else:
                    for cell in cells_from_mask(diff):
                        self.events.put(CellFlipped(self.sync_turn, cell))
            self.events.put(TurnComplete(self.sync_turn))
            self.synced_turn = self.sync_turn
            self._delta_prev = None  # delta chain restarts at a sync
            was_synced = self.synced.is_set()
            self.synced.set()
            # Lifecycle mark: a re-sync after a reconnect is the gap's
            # closing edge on the merged timeline (the opening edge is
            # client.link_down).
            tracing.event("client.board_sync", "lifecycle",
                          turn=self.sync_turn, resync=was_synced)
            flight.note("client.board_sync", turn=self.sync_turn,
                        resync=was_synced)
            return True
        if t == "dflips":
            # Delta-of-sparse flips (r6): XOR the bitmap delta against
            # the chain state FIRST — the chain must advance even for
            # a frame the synced_turn gate then drops, or every later
            # frame would decode against a stale bitmap.
            if self.board is None:
                raise wire.WireError(
                    "delta-flips frame before any board sync"
                )
            h, w = self.board.shape
            _, nb = wire.grid_words(w, h)
            if len(msg["dbitmap"]) != nb:
                raise wire.WireError(
                    f"delta-flips bitmap of {len(msg['dbitmap'])} words, "
                    f"board needs {nb}"
                )
            prev = (self._delta_prev if self._delta_prev is not None
                    else np.zeros(nb, np.uint32))
            bitmap = msg["dbitmap"] ^ prev
            self._delta_prev = bitmap
            turn = msg["turn"]
            if turn <= self.synced_turn:
                return True
            coords = wire.words_to_coords(bitmap, msg["dwords"], w, h)
            self._track_flips(coords, None)
            if self._batch:
                self.events.put(FlipBatch(turn, coords))
            else:
                for x, y in coords:
                    self.events.put(CellFlipped(turn, Cell(int(x), int(y))))
            return True
        if t == "fbatch":
            self._apply_fbatch(msg)
            return True
        if t == "flips":
            turn, coords = wire.msg_flips_array(msg)
            lv = wire.msg_flips_levels(msg) if self._levels else None
            if lv is not None and len(lv) != len(coords):
                raise wire.WireError(
                    f"{len(coords)} cells vs {len(lv)} levels"
                )
            if turn <= self.synced_turn:
                # Already inside the synced raster (the server's gate
                # makes this unreachable in practice; kept as the
                # client's own no-double-apply guarantee).
                return True
            self._track_flips(coords, lv)
            if self._batch:
                self.events.put(FlipBatch(turn, coords, levels=lv))
            else:
                for x, y in coords:
                    self.events.put(CellFlipped(turn, Cell(int(x), int(y))))
            return True
        if t == "hb":
            # Liveness beacon: answer with a pong — the server's
            # idle-eviction clock runs on these.
            with contextlib.suppress(OSError, ConnectionError,
                                     wire.WireError):
                with self._send_lock:
                    wire.send_msg(self._sock, {"t": "hb"})
            return True
        if t == "ev":
            for ev in wire.msg_to_events(msg):
                self.events.put(ev)
            return True
        if t == "seek-r":
            # Completion marker of a seek (the frames preceded it in
            # stream order, already applied above).
            self._seek_reply = msg
            self._seek_done.set()
            return True
        if t == "detached":
            self.detached.set()
            return False
        if t == "bye":
            return False
        return True  # unknown message kinds are ignored (forward compat)

    def _apply_fbatch(self, msg: dict) -> None:
        """Apply one k-turn batch frame (wire _TAG_FBATCH, already
        validated structurally at parse). The shadow raster advances
        in ONE vectorized XOR pass: turn i's flips ride as
        D[i] = S[i] XOR S[i-1] (D[0] = S[0]; frames self-contained),
        so the net board change over applied turns t0..k-1 is the XOR
        of exactly the D rows appearing an ODD number of times in
        Σ_{t>=t0} S[t] — D[j] appears (k - max(j, t0)) times. On a
        settled board (every turn's flips identical) every D row past
        the first is empty and the whole apply is a few hundred words.

        `synced_turn` gates per TURN, not per frame: a batch
        straddling a reconnect resync applies only its suffix — the
        gated prefix is already inside the synced raster (bit-exact,
        pinned by the fuzz suite's scripted-server test)."""
        if self.board is None:
            raise wire.WireError("batch frame before any board sync")
        # apply_fbatch_raster validates/coerces every field first (a
        # hostile JSON "fbatch" surfaces as WireError there); past it,
        # these plain conversions cannot fail.
        t0 = apply_fbatch_raster(self.board, msg, self.synced_turn)
        k, first = int(msg["k"]), int(msg["first_turn"])
        if t0 >= k:
            return  # whole batch already inside the synced raster
        if not self._batch_flip_events:
            # The high-rate watching mode (the 10⁵ turns/s path):
            # per-turn TurnComplete only — none of the reconstruction
            # state below is needed here.
            self.events.put_many(
                [TurnComplete(first + t) for t in range(t0, k)]
            )
            return
        # Exact per-turn surfacing: reconstruct each turn's flip set
        # from the delta chain (the slow-but-faithful mode; identical
        # to the unbatched event stream, pinned by test). asarray, not
        # .astype: a JSON-carried batch holds plain lists here.
        counts = np.asarray(msg["counts"], np.int64)
        total, nb = wire.grid_words(self.board.shape[1],
                                    self.board.shape[0])
        dbm = np.asarray(msg["dbitmaps"], np.uint32).reshape(-1, nb)
        dwords = np.asarray(msg["dwords"], np.uint32)
        w, h = self.board.shape[1], self.board.shape[0]
        evs: list = []
        cur = np.zeros(total, np.uint32)
        bi = 0
        off = 0
        for t in range(k):
            m = int(counts[t])
            if m:
                idx = wire._bitmap_indices(dbm[bi])
                bi += 1
                cur[idx] ^= dwords[off:off + m]
                off += m
            turn = first + t
            if turn <= self.synced_turn:
                continue
            nzw = np.flatnonzero(cur)
            if nzw.size:
                coords = wire.words_to_coords(
                    wire._indices_to_bitmap(nzw, nb), cur[nzw], w, h
                )
                if self._batch:
                    evs.append(FlipBatch(turn, coords))
                else:
                    evs.extend(
                        CellFlipped(turn, Cell(int(cx), int(cy)))
                        for cx, cy in coords
                    )
            evs.append(TurnComplete(turn))
        self.events.put_many(evs)

    def _track_flips(self, coords, levels) -> None:
        """Mirror one delivered flip batch onto the shadow raster, so
        the NEXT board sync diffs against what consumers actually have
        (see _handle_inner's board branch)."""
        if self.board is None or len(coords) == 0:
            return
        xy = np.asarray(coords).reshape(-1, 2)
        if levels is not None:
            self.board[xy[:, 1], xy[:, 0]] = levels
        else:
            self.board[xy[:, 1], xy[:, 0]] ^= np.uint8(255)

    def _reader_loop(self, first: Optional[dict]) -> None:
        msg = first
        while True:
            reason = None
            try:
                while True:
                    if msg is not None and not self._handle(msg):
                        self.close()  # clean stream end: bye/detached
                        return
                    msg = wire.recv_msg(self._sock)
                    if msg is None:
                        raise wire.WireError(
                            "server closed the stream without a goodbye"
                        )
            except TimeoutError:
                # Zero frames for 3 heartbeat intervals: the server
                # (or the path to it) is gone.
                _METRICS.hb_miss.inc()
                reason = "heartbeat deadline expired"
            except (wire.WireError, OSError) as e:
                reason = str(e) or type(e).__name__
            msg = None
            if self._closing.is_set() or self.detached.is_set():
                self.close()
                return
            tracing.event("client.link_down", "lifecycle", reason=reason)
            flight.note("client.link_down", reason=reason)
            msg = self._try_reconnect(reason)
            if msg is None:
                self._mark_lost(reason)
                return

    def _try_reconnect(self, reason: str) -> Optional[dict]:
        """Supervision: re-dial with exponential backoff + jitter until
        the window/attempt budget runs out. Returns the new link's
        first message on success (the reader continues with it), None
        when the caller should declare the link lost."""
        if (not self._reconnect_enabled or self._closing.is_set()
                or self.detached.is_set()):
            return None
        log.warning("link to %s:%d failed (%s) — reconnecting",
                    self._host, self._port, reason)
        with contextlib.suppress(OSError):
            self._sock.close()
        self._reconnecting.set()
        try:
            deadline = time.monotonic() + self._window
            attempt = 0
            hint: "float | None" = None
            while (self._max_reconnects is None
                   or attempt < self._max_reconnects):
                if hint is not None:
                    # Admission control told us WHEN to come back
                    # (busy / at-capacity retry_after): honor the
                    # server's number instead of blind exponential
                    # guessing — light jitter only, so a shed fleet
                    # still doesn't re-dial in lockstep.
                    delay = hint * (0.9 + 0.2 * self._rng.random())
                    hint = None
                else:
                    delay = min(self._backoff_cap,
                                self._backoff_base * (2 ** min(attempt, 20)))
                    delay *= 0.5 + self._rng.random()  # jitter: [0.5x, 1.5x)
                if time.monotonic() + delay >= deadline:
                    return None
                if self._closing.wait(delay):
                    return None
                attempt += 1
                try:
                    sock, msg = self._dial()
                except (UnauthorizedError, UnknownSessionError):
                    # Policy rejections — and a session that no longer
                    # exists (destroyed while we were down) — cannot be
                    # retried into existence.
                    return None
                except ServerBusyError as e:
                    # Our dead slot may not be released server-side
                    # yet (or the house is full) — exactly what the
                    # backoff exists to wait out; a retry_after hint
                    # replaces the next guess.
                    hint = e.retry_after
                    continue
                except (ConnectionError, OSError):
                    continue
                if msg is None:
                    with contextlib.suppress(OSError):
                        sock.close()
                    continue
                self._sock = sock
                self._arm_read_deadline()
                self.reconnects += 1
                _METRICS.reconnects.inc()
                tracing.event("client.reconnected", "lifecycle",
                              attempt=attempt)
                flight.note("client.reconnected", attempt=attempt)
                log.warning(
                    "reconnected to %s:%d on attempt %d — resyncing "
                    "via BoardSync", self._host, self._port, attempt,
                )
                return msg
            return None
        finally:
            self._reconnecting.clear()

    def _mark_lost(self, reason: str) -> None:
        log.warning("connection to %s:%d lost permanently (%s)",
                    self._host, self._port, reason)
        self.lost.set()
        _METRICS.lost.inc()
        tracing.event("client.lost", "lifecycle", reason=reason)
        flight.note("client.lost", reason=reason)
        # Reconnect exhaustion is this side's black-box moment: dump
        # the recent history crash-atomically (no-op without a
        # configured directory) before the caller tears down.
        flight.dump("connection-lost")
        self.close()


def apply_fbatch_raster(board: np.ndarray, msg: dict,
                        floor_turn: int) -> int:
    """Advance a shadow raster by one parsed _TAG_FBATCH frame in ONE
    vectorized XOR pass, applying only turns PAST `floor_turn` (frames
    are self-contained, so a frame straddling a resync applies just
    its suffix — the gated prefix is already inside the synced
    raster). Turn i's flips ride as D[i] = S[i] XOR S[i-1] (D[0] =
    S[0]), so the net change over applied turns t0..k-1 is the XOR of
    exactly the D rows appearing an ODD number of times in
    Σ_{t>=t0} S[t] — D[j] appears (k - max(j, t0)) times. Shared by
    the Controller and the relay tier (whose shadow is what new
    downstream observers board-sync from). Returns t0, the first
    applied row index (>= k when the whole frame was gated off);
    raises WireError on any frame/board inconsistency."""
    h, w = board.shape
    total, nb = wire.grid_words(w, h)
    try:
        # Binary frames are parse-validated upstream; a hostile JSON
        # "fbatch" reaches here with arbitrary fields, and anything
        # escaping as KeyError/AttributeError would kill reader
        # threads whose handlers expect WireError/OSError only.
        msg_nb = int(msg["nb"])
        counts = np.asarray(msg["counts"], np.int64)
        k, first = int(msg["k"]), int(msg["first_turn"])
        dbm = np.asarray(msg["dbitmaps"], np.uint32).reshape(-1, nb)
        dwords = np.asarray(msg["dwords"], np.uint32)
    except (KeyError, TypeError, ValueError, AttributeError) as e:
        raise wire.WireError(f"malformed batch message: {e}") from None
    if msg_nb != nb:
        raise wire.WireError(
            f"batch bitmap rows of {msg_nb} words, this board "
            f"needs {nb}"
        )
    if total % 32 and dbm.size and np.any(
            dbm[:, -1] >> np.uint32(total % 32)):
        raise wire.WireError("batch bitmap bit outside the board grid")
    t0 = max(0, floor_turn - first + 1)
    if t0 >= k:
        return t0  # whole batch already inside the synced raster
    nzt = np.flatnonzero(counts)  # turns with a nonzero delta row
    offs = np.zeros(len(nzt) + 1, np.int64)
    np.cumsum(counts[nzt], out=offs[1:])
    reps = k - np.maximum(nzt, t0)
    sel = np.flatnonzero((reps > 0) & (reps % 2 == 1))
    if sel.size:
        acc = np.zeros(total, np.uint32)
        for i in sel:
            idx = wire._bitmap_indices(dbm[i])
            acc[idx] ^= dwords[offs[i]:offs[i + 1]]
        fw = np.flatnonzero(acc)
        if fw.size:
            bits = (acc[fw, None]
                    >> np.arange(32, dtype=np.uint32)) & 1
            rr, bb = np.nonzero(bits)
            x = fw[rr] % w
            y = (fw[rr] // w) * 32 + bb
            if y.size and int(y.max()) >= h:
                raise wire.WireError(
                    "batch mask bit past the board height"
                )
            board[y, x] ^= np.uint8(255)
    return t0


#: The name the coursework spec uses for this half of the split.
EngineClient = Controller


class SessionControl:
    """Blocking verb client for a `--serve --sessions` server
    (gol_tpu.sessions): create / destroy / list / checkpoint over the
    session wire protocol. One control connection, synchronous RPCs —
    the management half; watching a session is `Controller(session=id)`.

    Verbs are IDEMPOTENT and supervised (docs/SESSIONS.md "Idempotent
    verbs"): every create/destroy/checkpoint is stamped with a
    client-generated request id (`rid`) and retried with
    deadline+backoff across link failures — the control link is
    re-dialed and re-handshaken, and the SAME rid rides every retry,
    so the server's replay window (plus its state-based fallbacks)
    makes an at-least-once verb exactly-once in effect: a retried
    create never double-creates, a retried destroy never errors. Load
    rejections (`busy`, `max-sessions`) carry a `retry_after` hint the
    retry loop honors instead of blind exponential backoff. `list` is
    read-only and simply re-executed. `retry_window=0` restores
    one-shot fail-fast semantics.

    Not thread-safe by design (one outstanding RPC at a time). The
    control link deliberately does NOT negotiate heartbeats: with no
    reader between verbs, answering beacons can't be guaranteed, and an
    hb peer silent past the eviction window would be dropped mid-idle
    — as a legacy peer (PR 3 scheme) it is never evicted, so arbitrary
    idle gaps between verbs are safe. Beacons the server sends anyway
    are answered inline mid-RPC and drained at the next verb."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8030, *,
                 secret: "str | None" = None, timeout: float = 30.0,
                 retry_window: float = 30.0,
                 retry_seed: "int | None" = None):
        self._host, self._port = host, port
        self._secret = secret
        self._timeout = timeout
        self._window = max(0.0, retry_window)
        #: Seeded jitter: a chaos scenario replays its retry schedule.
        self._rng = random.Random(retry_seed)
        #: rid prefix unique across processes AND restarts — a client
        #: that crashed mid-verb and restarted must never collide with
        #: its previous incarnation's window entries.
        self._rid_prefix = uuid.uuid4().hex[:12]
        self._rid_n = 0
        self._sock: "socket.socket | None" = None
        self._connect()

    def _connect(self) -> None:
        from gol_tpu.testing import faults

        self._sock = faults.wrap("client", socket.create_connection(
            (self._host, self._port), timeout=self._timeout
        ))
        self._sock.settimeout(self._timeout)
        hello = {"t": "hello", "sessions": True}
        if self._secret is not None:
            hello["secret"] = self._secret
        try:
            wire.send_msg(self._sock, hello)
            first = wire.recv_msg(self._sock, allow_binary=False)
        except (TimeoutError, wire.WireError, OSError) as e:
            self.close()
            raise ConnectionError(
                f"session-control handshake with {self._host}:"
                f"{self._port} failed: {e}"
            ) from None
        if first is None or first.get("t") == "error":
            reason = (first or {}).get("reason", "rejected")
            self.close()
            if reason == "unauthorized":
                raise UnauthorizedError(reason)
            if reason in ("busy", "at-capacity"):
                raise ServerBusyError(
                    reason,
                    sanitize_retry_after(first.get("retry_after")),
                )
            raise ConnectionError(reason)
        if not first.get("sessions"):
            self.close()
            raise ConnectionError(
                "server does not speak the session protocol "
                "(start it with --serve --sessions)"
            )

    def _next_rid(self) -> str:
        self._rid_n += 1
        return f"{self._rid_prefix}-{self._rid_n}"

    def _rpc(self, msg: dict) -> dict:
        wire.send_msg(self._sock, msg)
        deadline = time.monotonic() + self._timeout
        while True:
            if time.monotonic() > deadline:
                raise TimeoutError("session verb timed out")
            reply = wire.recv_msg(self._sock, allow_binary=False)
            if reply is None:
                raise ConnectionError("server closed the control link")
            t = reply.get("t")
            if t == "hb":
                with contextlib.suppress(OSError, wire.WireError):
                    wire.send_msg(self._sock, {"t": "hb"})
                continue
            if t == "session-r" and reply.get("op") == msg.get("op"):
                if ("rid" in msg and reply.get("rid") is not None
                        and reply["rid"] != msg["rid"]):
                    continue  # a predecessor's late reply, not ours
                return reply
            # clk echoes / future kinds: ignorable (forward compat).

    #: Transient reply reasons the retry loop waits out (everything
    #: else — unknown-session, bad-rule, exists — is a real answer).
    _TRANSIENT = ("busy", "max-sessions", "at-capacity")

    def _checked(self, msg: dict, idempotent: bool = False) -> dict:
        """One verb, supervised: re-dial + resend (same rid) on link
        failures, wait out transient rejections honoring retry_after,
        raise the first durable error. With `idempotent=False` (list)
        the verb is still retried — re-executing a read is safe."""
        from gol_tpu.sessions.manager import SessionError

        if idempotent and self._window > 0:
            msg = {**msg, "rid": self._next_rid()}
        deadline = time.monotonic() + self._window
        attempt = 0
        hint: "float | None" = None
        while True:
            try:
                if self._sock is None:
                    self._connect()
                reply = self._rpc(msg)
            except UnauthorizedError:
                raise
            except (TimeoutError, ConnectionError, wire.WireError,
                    OSError) as e:
                # Link-level failure: the verb may or may not have
                # landed — exactly what the rid exists for. Tear the
                # link down and retry the SAME message.
                if isinstance(e, ServerBusyError):
                    hint = e.retry_after
                self.close()
                self._sock = None
                if time.monotonic() >= deadline:
                    raise ConnectionError(
                        f"session verb {msg.get('op')!r} failed after "
                        f"{self._window:.0f}s of retries: {e}"
                    ) from None
            else:
                if reply.get("ok"):
                    return reply
                reason = reply.get("reason", "rejected")
                if (reason not in self._TRANSIENT
                        or time.monotonic() >= deadline):
                    raise SessionError(reason)
                hint = sanitize_retry_after(reply.get("retry_after"))
            if hint is not None:
                delay = hint * (0.9 + 0.2 * self._rng.random())
                hint = None
            else:
                delay = min(1.0, 0.05 * (2 ** min(attempt, 10)))
                delay *= 0.5 + self._rng.random()
            attempt += 1
            time.sleep(min(delay, max(0.0,
                                      deadline - time.monotonic())))

    def create(self, sid: str, *, width: int, height: int,
               rule: "str | None" = None, seed: "int | None" = None,
               density: float = 0.25) -> dict:
        msg = {"t": "session", "op": "create", "id": sid,
               "width": width, "height": height, "density": density}
        if rule is not None:
            msg["rule"] = rule
        if seed is not None:
            msg["seed"] = seed
        return self._checked(msg, idempotent=True)["session"]

    def destroy(self, sid: str) -> None:
        self._checked({"t": "session", "op": "destroy", "id": sid},
                      idempotent=True)

    def list(self) -> list:
        return self._checked({"t": "session", "op": "list"})["sessions"]

    def checkpoint(self, sid: str) -> dict:
        r = self._checked({"t": "session", "op": "checkpoint", "id": sid},
                          idempotent=True)
        return {"path": r.get("path"), "turn": r.get("turn")}

    def park(self, sid: str) -> dict:
        """Hibernate a session (docs/SESSIONS.md "Hibernation"):
        checkpoint + free its device slot; the next attach (a
        Controller with session=sid) rehydrates it bit-exactly.
        Idempotent under retry — a rid-retried park whose first
        attempt landed answers ok."""
        r = self._checked({"t": "session", "op": "park", "id": sid},
                          idempotent=True)
        return {"id": r.get("id"), "turn": r.get("turn")}

    def adopt(self, sid: str, source: str) -> dict:
        """Materialize a session hibernated under ANOTHER engine's
        out tree (control-plane migration, PR 18): the server reads
        `source`'s sidecar + latest snapshot, creates the session
        resident at the snapshot turn, and re-checkpoints into its
        OWN tree before acking. Idempotent under retry: an adopt
        whose first attempt landed answers ok on the rid re-send."""
        r = self._checked(
            {"t": "session", "op": "adopt", "id": sid,
             "source": source},
            idempotent=True,
        )
        return r["session"]

    def drain(self) -> dict:
        """Checkpoint every resident session and stop admitting new
        session attaches — the safe prelude to a rolling restart with
        `--resume latest` (control plane, PR 18). Idempotent: a
        retried drain re-checkpoints and stays draining."""
        r = self._checked({"t": "session", "op": "drain"},
                          idempotent=True)
        return {"checkpointed": r.get("checkpointed"),
                "draining": bool(r.get("draining"))}

    def close(self) -> None:
        if self._sock is None:
            return
        with contextlib.suppress(OSError):
            self._sock.shutdown(socket.SHUT_RDWR)
        with contextlib.suppress(OSError):
            self._sock.close()

    def __enter__(self) -> "SessionControl":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
