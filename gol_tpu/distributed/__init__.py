"""Distributed split: engine server ⇄ controller client over TCP
(the working version of the reference's RPC scaffolding, SURVEY.md §2 C11)."""

from gol_tpu.distributed.client import (
    Controller,
    ServerBusyError,
    UnauthorizedError,
)
from gol_tpu.distributed.server import EngineServer, snapshot_turn

__all__ = [
    "Controller",
    "EngineServer",
    "ServerBusyError",
    "UnauthorizedError",
    "snapshot_turn",
]
