"""Distributed split: engine server ⇄ controller client over TCP
(the working version of the reference's RPC scaffolding, SURVEY.md §2 C11)."""

from gol_tpu.distributed.client import (
    ConnectionLost,
    Controller,
    EngineClient,
    ServerBusyError,
    SessionControl,
    UnauthorizedError,
    UnknownSessionError,
)
from gol_tpu.distributed.server import (
    EngineServer,
    SessionServer,
    snapshot_turn,
)

__all__ = [
    "ConnectionLost",
    "Controller",
    "EngineClient",
    "EngineServer",
    "ServerBusyError",
    "SessionControl",
    "SessionServer",
    "UnauthorizedError",
    "UnknownSessionError",
    "snapshot_turn",
]
