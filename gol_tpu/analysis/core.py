"""Linter core — findings, jit-context discovery, allowlist, file walk.

Everything here is pure `ast` + stdlib on purpose: the linter must run
(and fail usefully) on a machine where jax, the native board, or the
package under analysis cannot even import. Checks live in
`gol_tpu/analysis/checks/`; each module exposes

    CHECK = "kebab-name"        # finding category
    def run(ctx: ModuleContext) -> Iterator[Finding]

and registers itself in `checks.ALL_CHECKS`.

Allowlist keys are (check, path, scope) — scope is the enclosing
function's dotted qualname (or "<module>") — NOT line numbers, so an
unrelated edit above a grandfathered finding cannot silently retire or
orphan its entry. The flip side: one entry covers every same-check
finding in that function, which is the granularity reasons are written
at anyway.
"""

from __future__ import annotations

import ast
import dataclasses
import pathlib
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set

#: Decorator/callable spellings that put a function body under trace.
_JIT_NAMES = {"jit", "pjit"}
#: Callables whose function-argument runs traced even without a jit
#: decorator (scan bodies, shard_map inner fns, vmapped fns).
_TRACING_CALLERS = {"scan", "shard_map", "vmap", "pmap", "fori_loop",
                    "while_loop", "checkpoint", "remat"}


@dataclasses.dataclass(frozen=True)
class Finding:
    """One hazard the linter found."""

    check: str    #: category, e.g. "host-sync"
    path: str     #: repo-relative posix path
    line: int
    scope: str    #: enclosing function qualname, or "<module>"
    message: str

    @property
    def key(self) -> tuple:
        """Allowlist identity — line-number free (see module docstring)."""
        return (self.check, self.path, self.scope)

    def render(self) -> str:
        return (f"{self.path}:{self.line}: [{self.check}] {self.message}"
                f"  (scope: {self.scope})")


@dataclasses.dataclass
class JitInfo:
    """One function whose body runs under trace."""

    node: ast.AST                 # FunctionDef / Lambda
    qualname: str
    static_names: Set[str]        # params excluded from tracing
    reason: str                   # "jax.jit decorator", "lax.scan body", ...


class ModuleContext:
    """Parsed module + the derived maps every check needs."""

    def __init__(self, path: pathlib.Path, rel: str, source: str):
        self.path = path
        self.rel = rel  # repo-relative posix path used in findings
        self.source = source
        self.tree = ast.parse(source, filename=str(path))
        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        self._qualnames = self._build_qualnames()
        self.jitted: Dict[ast.AST, JitInfo] = {}
        self._find_jitted()

    # -- structure helpers -------------------------------------------------

    def _build_qualnames(self) -> Dict[ast.AST, str]:
        names: Dict[ast.AST, str] = {}

        def visit(node: ast.AST, prefix: str) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    q = f"{prefix}.{child.name}" if prefix else child.name
                    names[child] = q
                    visit(child, q)
                else:
                    visit(child, prefix)

        visit(self.tree, "")
        return names

    def qualname(self, node: ast.AST) -> str:
        return self._qualnames.get(node, "<module>")

    def scope_of(self, node: ast.AST) -> str:
        """Dotted qualname of the innermost enclosing function/class."""
        cur: Optional[ast.AST] = node
        while cur is not None:
            if cur in self._qualnames:
                return self._qualnames[cur]
            cur = self.parents.get(cur)
        return "<module>"

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return cur
            cur = self.parents.get(cur)
        return None

    def finding(self, check: str, node: ast.AST, message: str) -> Finding:
        return Finding(check, self.rel, getattr(node, "lineno", 0),
                       self.scope_of(node), message)

    # -- jit-context discovery --------------------------------------------

    def jit_context(self, node: ast.AST) -> Optional[JitInfo]:
        """The JitInfo whose body `node` sits in, walking out through
        nested defs — an inner helper of a jitted function is traced
        too, UNLESS an inner def is itself the jit boundary."""
        cur: Optional[ast.AST] = node
        while cur is not None:
            if cur in self.jitted:
                return self.jitted[cur]
            cur = self.parents.get(cur)
        return None

    def _find_jitted(self) -> None:
        # Pass 1: decorated defs.
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    static = self._jit_static_names(dec)
                    if static is not None:
                        self.jitted[node] = JitInfo(
                            node, self.qualname(node), static,
                            "jit decorator",
                        )
                        break
        # Pass 2: functions handed to tracing callers — jax.jit(f),
        # lax.scan(body, ...), shard_map(f, ...). Map names defined in
        # the same module to their defs.
        defs_by_name: Dict[str, List[ast.AST]] = {}
        for node, q in self._qualnames.items():
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs_by_name.setdefault(node.name, []).append(node)
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = _tail_name(node.func)
            if callee in _JIT_NAMES:
                static = _static_names_from_call(node)
                self._mark_callable_arg(node, defs_by_name, static,
                                        "jax.jit call")
            elif callee in _TRACING_CALLERS:
                self._mark_callable_arg(node, defs_by_name, set(),
                                        f"{callee} body")

    def _mark_callable_arg(self, call: ast.Call, defs_by_name, static,
                           reason: str) -> None:
        if not call.args:
            return
        fn = call.args[0]
        target: Optional[ast.AST] = None
        if isinstance(fn, ast.Lambda):
            target = fn
        elif isinstance(fn, ast.Name):
            cands = defs_by_name.get(fn.id, [])
            if len(cands) == 1:
                target = cands[0]
        if target is not None and target not in self.jitted:
            self.jitted[target] = JitInfo(
                target, self.qualname(target)
                if not isinstance(target, ast.Lambda) else
                f"{self.scope_of(target)}.<lambda>",
                static, reason,
            )

    def _jit_static_names(self, dec: ast.AST) -> Optional[Set[str]]:
        """Static param names if `dec` is a jit-ish decorator, else None.

        Recognized: `jax.jit`, `jit`, `pjit`, and
        `functools.partial(jax.jit, static_argnames=(...))`."""
        if _tail_name(dec) in _JIT_NAMES:
            return set()
        if isinstance(dec, ast.Call):
            head = _tail_name(dec.func)
            if head in _JIT_NAMES:
                return _static_names_from_call(dec)
            if head == "partial" and dec.args \
                    and _tail_name(dec.args[0]) in _JIT_NAMES:
                return _static_names_from_call(dec)
        return None


def _tail_name(node: ast.AST) -> Optional[str]:
    """'jax.jit' -> 'jit', 'jit' -> 'jit', anything else -> None."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


#: Array attributes that are STATIC under trace — reading (or branching
#: on) them is how kernels legally specialize, never a host sync.
STATIC_ATTRS = {"dtype", "shape", "ndim", "size", "sharding", "weak_type"}


def traced_params(info: JitInfo) -> Set[str]:
    """Parameter names of a jit-context function that are traced values
    (everything not named static) — FunctionDef and Lambda alike."""
    args = info.node.args
    names = {a.arg for a in [*args.posonlyargs, *args.args,
                             *args.kwonlyargs]}
    return names - info.static_names


def dynamic_names(expr: ast.AST) -> Set[str]:
    """Names mentioned in `expr` other than as the base of a static
    metadata attribute: `w.shape[0]` mentions no dynamic name, `w + 1`
    mentions `w`. The shared vocabulary of the host-sync and
    tracer-branch checks — both must agree that static metadata reads
    are free."""
    exempt = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and node.attr in STATIC_ATTRS:
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Name):
                    exempt.add(sub)
    return {
        n.id for n in ast.walk(expr)
        if isinstance(n, ast.Name) and n not in exempt
    }


def _static_names_from_call(call: ast.Call) -> Set[str]:
    """static_argnames constants of a jit/partial(jit) call."""
    out: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for el in ast.walk(kw.value):
                if isinstance(el, ast.Constant) and isinstance(el.value, str):
                    out.add(el.value)
    return out


# -- allowlist ------------------------------------------------------------


class AllowlistError(ValueError):
    pass


@dataclasses.dataclass
class AllowEntry:
    check: str
    path: str
    scope: str
    reason: str
    lineno: int  # in the allowlist file, for diagnostics

    @property
    def key(self) -> tuple:
        return (self.check, self.path, self.scope)


class Allowlist:
    """Grandfathered findings, one `check | path | scope | reason` line
    each. Every entry MUST carry a non-empty reason — an allowlist
    entry is a documented engineering decision, not a mute button."""

    def __init__(self, entries: Sequence[AllowEntry] = ()):
        self.entries = list(entries)
        self._by_key = {e.key: e for e in self.entries}

    @classmethod
    def load(cls, path: pathlib.Path) -> "Allowlist":
        entries = []
        for i, raw in enumerate(path.read_text().splitlines(), 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            parts = [p.strip() for p in line.split("|")]
            if len(parts) != 4 or not all(parts):
                raise AllowlistError(
                    f"{path}:{i}: expected 'check | path | scope | reason'"
                    f" with all four fields non-empty, got {raw!r}"
                )
            entries.append(AllowEntry(*parts, lineno=i))
        return cls(entries)

    def allows(self, finding: Finding) -> bool:
        return finding.key in self._by_key

    def stale(self, findings: Iterable[Finding],
              scanned: Optional[Set[str]] = None) -> List[AllowEntry]:
        """Entries matching no current finding — fixed hazards whose
        entry must now be deleted (the shrink-only contract). With
        `scanned` (the rel paths this run actually linted), entries for
        files OUTSIDE the scan are exempt: a partial-tree run can only
        prove staleness for files it looked at."""
        live = {f.key for f in findings}
        return [e for e in self.entries
                if e.key not in live
                and (scanned is None or e.path in scanned)]


# -- file walk (the run loop itself lives in jaxlint.py) ------------------

_SKIP_DIRS = {"__pycache__", ".git", "node_modules", ".venv"}


def iter_py_files(paths: Sequence[pathlib.Path],
                  root: pathlib.Path) -> Iterator[pathlib.Path]:
    for p in paths:
        if p.is_file() and p.suffix == ".py":
            yield p
        elif p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if not any(part in _SKIP_DIRS for part in f.parts):
                    yield f
