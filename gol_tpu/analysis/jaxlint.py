"""jaxlint — the static JAX-hazard linter's entry surface.

Thin by design: parsing, jit-context discovery and the allowlist live
in `core.py`; the hazard knowledge lives in one module per check under
`checks/`. This module owns the run loop — walk files, build a
ModuleContext per module, fan it through every registered check — and
is what the CLI (`__main__.py`), the CI gate
(`scripts/check_analysis.sh`) and the tier-1 test call.
"""

from __future__ import annotations

import pathlib
from typing import List, Optional, Sequence

from gol_tpu.analysis.core import Finding, ModuleContext, iter_py_files

__all__ = ["lint_paths", "rel_paths"]


def _rel(f: pathlib.Path, root: pathlib.Path) -> str:
    try:
        return f.resolve().relative_to(root.resolve()).as_posix()
    except ValueError:
        return f.as_posix()


def rel_paths(paths: Sequence[pathlib.Path],
              root: pathlib.Path) -> set:
    """Repo-relative paths a lint over `paths` covers — what the strict
    gate feeds Allowlist.stale, so a partial-tree run never declares
    entries for UNSCANNED files stale."""
    return {_rel(f, root) for f in iter_py_files(paths, root)}


def lint_paths(paths: Sequence[pathlib.Path], root: pathlib.Path,
               checks: Optional[Sequence] = None) -> List[Finding]:
    """Run every check over every .py under `paths`; `root` anchors the
    repo-relative paths findings (and allowlist entries) use. A file
    that does not parse yields a single `parse-error` finding rather
    than aborting the run — a syntax error anywhere must not blind the
    linter to the rest of the tree.

    Two check shapes. Per-module checks expose `run(ctx)` and see one
    file at a time. Project checks expose `run_project(ctxs)` and see
    every parsed module at once — what the concurrency passes need: a
    lock-order cycle is a property of the merged lock graph, never of
    one file, and a lock held here across a call that blocks THERE is
    only visible to an interprocedural walk. A check may expose both.
    """
    from gol_tpu.analysis.checks import ALL_CHECKS

    active = list(checks) if checks is not None else list(ALL_CHECKS)
    findings: List[Finding] = []
    ctxs: List[ModuleContext] = []
    for f in iter_py_files(paths, root):
        rel = _rel(f, root)
        try:
            ctx = ModuleContext(f, rel, f.read_text())
        except SyntaxError as e:
            findings.append(Finding("parse-error", rel, e.lineno or 0,
                                    "<module>", f"cannot parse: {e.msg}"))
            continue
        ctxs.append(ctx)
        for mod in active:
            if hasattr(mod, "run"):
                findings.extend(mod.run(ctx))
    for mod in active:
        if hasattr(mod, "run_project"):
            findings.extend(mod.run_project(ctxs))
    findings.sort(key=lambda f: (f.path, f.line, f.check))
    return findings
