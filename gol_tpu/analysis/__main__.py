"""CLI: `python -m gol_tpu.analysis [--strict] [paths...]`.

Default target is the `gol_tpu/` package of the repo this file sits in.
Exit codes: 0 = clean (every finding allowlisted, no stale entries in
--strict), 1 = new findings (or, with --strict, stale allowlist
entries), 2 = usage/allowlist-format errors.

The allowlist (`gol_tpu/analysis/allowlist.txt`) is shrink-only by
contract: new hazards must be fixed, not added to it —
`scripts/check_analysis.sh` is the CI wrapper enforcing exactly that.
"""

from __future__ import annotations

import argparse
import pathlib
import sys

from gol_tpu.analysis.core import Allowlist, AllowlistError
from gol_tpu.analysis.jaxlint import lint_paths, rel_paths

_HERE = pathlib.Path(__file__).resolve().parent
_DEFAULT_ALLOWLIST = _HERE / "allowlist.txt"
_REPO_ROOT = _HERE.parent.parent


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m gol_tpu.analysis",
        description="JAX-hazard linter: host syncs, tracer branching, "
                    "recompile hazards, dtype drift, donation decisions",
    )
    ap.add_argument("paths", nargs="*", type=pathlib.Path,
                    help="files/dirs to lint (default: the gol_tpu package)")
    ap.add_argument("--strict", action="store_true",
                    help="also fail on stale allowlist entries (CI mode: "
                         "the finding count can only go down)")
    ap.add_argument("--allowlist", type=pathlib.Path,
                    default=_DEFAULT_ALLOWLIST, metavar="FILE",
                    help="grandfathered findings (default: the committed "
                         "gol_tpu/analysis/allowlist.txt)")
    ap.add_argument("--no-allowlist", action="store_true",
                    help="report every finding, grandfathered or not")
    ap.add_argument("--root", type=pathlib.Path, default=_REPO_ROOT,
                    help=argparse.SUPPRESS)  # tests re-anchor rel paths
    ap.add_argument("--list-checks", action="store_true",
                    help="print the registered checks and exit")
    args = ap.parse_args(argv)

    if args.list_checks:
        from gol_tpu.analysis.checks import ALL_CHECKS

        for mod in ALL_CHECKS:
            doc = (mod.__doc__ or "").strip().splitlines()[0]
            print(f"{mod.CHECK:15s} {doc}")
        return 0

    paths = args.paths or [_HERE.parent]
    allow = Allowlist()
    if not args.no_allowlist and args.allowlist.exists():
        try:
            allow = Allowlist.load(args.allowlist)
        except AllowlistError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2

    findings = lint_paths(paths, args.root)
    fresh = [f for f in findings if not allow.allows(f)]
    grandfathered = len(findings) - len(fresh)
    # Staleness is only provable for files this run scanned: a
    # partial-tree invocation must not fail the shrink-only gate over
    # entries it never looked at.
    stale = allow.stale(findings, scanned=rel_paths(paths, args.root))

    for f in fresh:
        print(f.render())
    if grandfathered:
        print(f"# {grandfathered} grandfathered finding(s) allowlisted "
              f"({args.allowlist.name})")
    if stale and args.strict:
        for e in stale:
            print(f"# STALE allowlist entry ({args.allowlist.name}:"
                  f"{e.lineno}): {e.check} | {e.path} | {e.scope} — the "
                  "finding is gone; delete the entry", file=sys.stderr)
    if fresh:
        print(f"{len(fresh)} new finding(s) — fix them, or allowlist "
              "with a reason", file=sys.stderr)
        return 1
    if stale and args.strict:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
