"""donation — carried world state without an explicit donation decision.

The ring steppers' multi-turn entry points carry the world through
`lax.scan`/`fori_loop` and hand back a fresh array every dispatch; at
production board sizes the input buffer is the single biggest device
allocation, and jit will happily keep both live unless the input is
donated. BUT donation is not free here: the engine retains references
to dispatched worlds (the committed (turn, world) pair served to
BoardSync/snapshot fetches, cycle-detector anchors, the sparse-overflow
redo input), and donating a buffer something still reads is a
use-after-free the CPU test mesh never exercises (donation is a no-op
off TPU). So the check does not demand donation — it demands the
decision be EXPLICIT: every multi-turn jitted stepper over a carried
world either donates or carries an allowlist entry saying why not.

Flagged: jit-decorated functions in `parallel/` modules with a
multi-turn static argument (k/n) whose first traced parameter is a
recognized carry name, with no donate_argnums/donate_argnames.
"""

from __future__ import annotations

import ast
from typing import Iterator

from gol_tpu.analysis.core import Finding, ModuleContext

CHECK = "donation"

#: First-parameter spellings of carried device state in this codebase.
_CARRY_NAMES = {"world", "state", "p", "q", "w", "planes", "block"}
_MULTI_TURN_STATICS = {"k", "n"}


def _has_donation(node) -> bool:
    for dec in node.decorator_list:
        for sub in ast.walk(dec):
            if isinstance(sub, ast.keyword) and sub.arg in (
                    "donate_argnums", "donate_argnames"):
                return True
    return False


def run(ctx: ModuleContext) -> Iterator[Finding]:
    if "parallel/" not in ctx.rel:
        return
    for node, info in ctx.jitted.items():
        if isinstance(node, ast.Lambda):
            continue
        if not (info.static_names & _MULTI_TURN_STATICS):
            continue  # single-turn wrappers: both buffers are transient
        params = [a.arg for a in node.args.args]
        if not params or params[0] not in _CARRY_NAMES:
            continue
        if params[0] in info.static_names:
            continue
        if _has_donation(node):
            continue
        yield ctx.finding(
            CHECK, node,
            f"multi-turn stepper '{info.qualname}' carries world state "
            f"'{params[0]}' without donate_argnums — donate it, or "
            "allowlist with the reason the input must stay live",
        )
