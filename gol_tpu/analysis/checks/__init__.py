"""Check registry. Each module: CHECK name + run(ctx) -> findings."""

from gol_tpu.analysis.checks import (
    blocking_io,
    donation,
    dtype_drift,
    host_sync,
    obs_in_jit,
    recompile,
    tracer_branch,
)

#: Every check the CLI and the tier-1 test run, in report order.
ALL_CHECKS = [host_sync, tracer_branch, recompile, dtype_drift, donation,
              obs_in_jit, blocking_io]

__all__ = ["ALL_CHECKS", "blocking_io", "donation", "dtype_drift",
           "host_sync", "obs_in_jit", "recompile", "tracer_branch"]
