"""Check registry. Each module: CHECK name + run(ctx) and/or
run_project(ctxs) -> findings."""

from gol_tpu.analysis.checks import (
    blocking_io,
    donation,
    dtype_drift,
    host_sync,
    obs_in_jit,
    partition_spec,
    recompile,
    tracer_branch,
)
from gol_tpu.analysis.concurrency import CONCURRENCY_CHECKS

#: Every check the CLI and the tier-1 test run, in report order. The
#: concurrency plane (lock-order, lock-blocking, thread-ownership,
#: guarded-field) lives in gol_tpu.analysis.concurrency and registers
#: here like any other check.
ALL_CHECKS = [host_sync, tracer_branch, recompile, dtype_drift, donation,
              obs_in_jit, blocking_io, partition_spec] + CONCURRENCY_CHECKS

__all__ = ["ALL_CHECKS", "blocking_io", "donation", "dtype_drift",
           "host_sync", "obs_in_jit", "partition_spec", "recompile",
           "tracer_branch"]
