"""partition-spec — sharding construction outside the partition table.

ISSUE 19 moved every ``Mesh``/``NamedSharding``/``PartitionSpec``
construction in the parallel layer into ``parallel/partition.py``: the
ordered rule table is the ONE place device placement is decided, so an
operator override (``--partition-rule``) provably reaches every array
a stepper owns. A backend that quietly builds its own spec re-opens
the hole this PR closed — its arrays stop being overridable and the
1-D-ring hard-coding creeps back in.

Flagged, in ``gol_tpu/parallel`` modules other than ``partition.py``:

- any import of ``jax.sharding`` (module or from-names) — backends get
  specs from the table (``partition.table_for(...).resolve``) or the
  ``partition.spec``/``partition.named_sharding``/``partition.REPLICATED``
  constructors;
- any call spelled ``Mesh(...)``, ``NamedSharding(...)``,
  ``PartitionSpec(...)`` or dotted equivalents — construction, not the
  mere type mention (annotations and docstrings stay legal).

Strict from day one: the refactor left zero violations, so the check
carries no allowlist entries and none may be added for new code.
"""

from __future__ import annotations

import ast
from typing import Iterator

from gol_tpu.analysis.core import Finding, ModuleContext

CHECK = "partition-spec"

_CONSTRUCTORS = {"Mesh", "NamedSharding", "PartitionSpec"}


def _in_scope(ctx: ModuleContext) -> bool:
    return ("parallel/" in ctx.rel
            and not ctx.rel.endswith("parallel/partition.py"))


def run(ctx: ModuleContext) -> Iterator[Finding]:
    if not _in_scope(ctx):
        return
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ImportFrom):
            if node.module and node.module.startswith("jax.sharding"):
                yield ctx.finding(
                    CHECK, node,
                    "import from jax.sharding outside partition.py — "
                    "resolve specs through partition.table_for / "
                    "partition.spec so operator overrides reach this "
                    "array",
                )
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.startswith("jax.sharding"):
                    yield ctx.finding(
                        CHECK, node,
                        "import of jax.sharding outside partition.py — "
                        "the partition table is the one sharding "
                        "constructor in the parallel layer",
                    )
        elif isinstance(node, ast.Call):
            fn = node.func
            name = None
            if isinstance(fn, ast.Name):
                name = fn.id
            elif isinstance(fn, ast.Attribute):
                name = fn.attr
            if name in _CONSTRUCTORS:
                yield ctx.finding(
                    CHECK, node,
                    f"direct {name}(...) construction outside "
                    "partition.py — build it through the partition "
                    "table so --partition-rule can override it",
                )
