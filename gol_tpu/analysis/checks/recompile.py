"""recompile — silent-recompilation and static-argument hazards.

Three concrete shapes:

1. static_argnames drift: a jit decorator naming a static argument the
   wrapped function does not declare. jax only validates the names that
   ARE present at call time, so a renamed parameter silently demotes
   the stale name to a traced (or rejected) argument — every call site
   keyed on it then recompiles or breaks.
2. jit() invoked inside a loop body: each iteration builds a fresh
   wrapper with its own cache, so every call compiles — the classic
   accidental O(n) compile bill.
3. bad static payloads at module-local jitted call sites: a dict
   literal bound to a STATIC parameter fails fast (unhashable); an
   f-string bound to one hashes fine but differs per expansion, so
   every distinct value is a new compile-cache entry. Dicts bound to
   TRACED parameters are legal pytree inputs and are left alone; set
   literals are flagged on any parameter (sets are neither hashable
   statics nor pytree containers).
"""

from __future__ import annotations

import ast
from typing import Iterator

from gol_tpu.analysis.core import (
    Finding,
    ModuleContext,
    _JIT_NAMES,
    _tail_name,
)

CHECK = "recompile"


def _function_params(node) -> set:
    args = node.args
    return {a.arg for a in [*args.posonlyargs, *args.args,
                            *args.kwonlyargs,
                            *([args.vararg] if args.vararg else []),
                            *([args.kwarg] if args.kwarg else [])]}


def _in_loop(ctx: ModuleContext, node: ast.AST) -> bool:
    cur = ctx.parents.get(node)
    while cur is not None and not isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        if isinstance(cur, (ast.For, ast.While)):
            return True
        cur = ctx.parents.get(cur)
    return False


def run(ctx: ModuleContext) -> Iterator[Finding]:
    # 1. static_argnames drift on decorated defs.
    for node, info in ctx.jitted.items():
        if isinstance(node, ast.Lambda) or not info.static_names:
            continue
        missing = sorted(info.static_names - _function_params(node))
        if missing:
            yield ctx.finding(
                CHECK, node,
                f"static_argnames {missing} not in the signature of "
                f"'{info.qualname}' — stale names silently stop being "
                "static",
            )
    # Module-local jitted defs for shape 3: name -> (ordered params,
    # static names), so call arguments can be bound to parameters.
    jitted_sigs = {}
    for n, info in ctx.jitted.items():
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            params = [a.arg for a in [*n.args.posonlyargs, *n.args.args]]
            jitted_sigs[n.name] = (params, info.static_names)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        callee = node.func
        # 2. jit(...) in a loop body.
        if _tail_name(callee) in _JIT_NAMES and _in_loop(ctx, node):
            yield ctx.finding(
                CHECK, node,
                "jax.jit() called inside a loop builds a fresh compile "
                "cache every iteration — hoist the jitted wrapper out",
            )
        # 3. bad payloads at jitted call sites, bound to parameters.
        name = callee.id if isinstance(callee, ast.Name) else None
        if name in jitted_sigs:
            params, static = jitted_sigs[name]
            bound = [(params[i] if i < len(params) else None, a)
                     for i, a in enumerate(node.args)]
            bound += [(k.arg, k.value) for k in node.keywords]
            for param, arg in bound:
                if isinstance(arg, ast.Set):
                    yield ctx.finding(
                        CHECK, arg,
                        f"set literal passed to jitted '{name}' — "
                        "unhashable as a static argument and not a "
                        "pytree container as a traced one",
                    )
                elif param not in static:
                    continue  # dicts/f-strings are fine as pytree args
                elif isinstance(arg, ast.Dict):
                    yield ctx.finding(
                        CHECK, arg,
                        f"dict literal bound to static '{param}' of "
                        f"jitted '{name}' — unhashable static argument",
                    )
                elif isinstance(arg, ast.JoinedStr):
                    yield ctx.finding(
                        CHECK, arg,
                        f"f-string bound to static '{param}' of jitted "
                        f"'{name}' — every distinct expansion is a new "
                        "compile-cache entry",
                    )
