"""blocking-io-timeout — unbounded socket reads/connects in the wire plane.

The resilience layer's ground rule (docs/RESILIENCE.md): every blocking
socket read or connect in `gol_tpu/distributed/` carries a deadline, so
a dead peer, a silent TCP connect, or a blackholed path can only stall
a thread for a bounded interval — never forever. Before this rule the
accept thread could be wedged permanently by one peer that connected
and sent nothing, and the 30s SO_SNDTIMEO was the system's ONLY failure
detector.

What the check enforces, per module under `gol_tpu/distributed/`:

- Raw `.recv(...)` / `.recv_into(...)` is allowed ONLY inside the wire
  plane's designated read primitive (`wire.py::_recv_exact`, which owns
  the idle-vs-mid-frame timeout semantics). Everything else must read
  through `wire.recv_msg`.
- `socket.create_connection(...)` must pass a `timeout` (kwarg or the
  second positional).
- A `recv_msg(X, ...)` / `X.connect(...)` call is accepted only when
  the module applies a read deadline to a socket whose dotted-chain
  tail matches X's (`conn.sock` ⇄ `sock.settimeout(t)`,
  `self._sock` ⇄ `self._sock.settimeout(t)`): a `settimeout` whose
  argument is not the literal None, or a `setsockopt` naming
  SO_RCVTIMEO/SO_SNDTIMEO. Tail matching is deliberately name-based —
  the point is that the module *documents the deadline discipline for
  that socket*, which line-level dataflow cannot prove anyway.
- `.accept()` on the listener is exempt: its lifecycle is close-driven
  (closing the listener is how the accept loop is told to exit), and a
  deadline there would only add spurious wakeups.
"""

from __future__ import annotations

import ast
from typing import Iterator, Set

from gol_tpu.analysis.core import Finding, ModuleContext

CHECK = "blocking-io-timeout"

_SCOPE_PREFIX = ("gol_tpu/distributed/", "gol_tpu/relay/")
#: Sanctioned raw-recv sites: (path suffix, enclosing scope). The
#: relay tier adds two — the WS plane's exact-read primitive and its
#: header-delimited upgrade reader (both deadline-disciplined the
#: wire._recv_exact way).
_RECV_PRIMITIVES = (
    ("wire.py", "_recv_exact"),
    ("ws.py", "_read_exact"),
    ("ws.py", "handshake"),
)
_TIMEOUT_OPTS = {"SO_RCVTIMEO", "SO_SNDTIMEO"}


def _tail(node: ast.AST):
    """Final attribute/name of a dotted chain: `conn.sock` -> 'sock',
    `self._sock` -> '_sock', `sock` -> 'sock'."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _deadlined_tails(ctx: ModuleContext) -> Set[str]:
    """Chain tails this module applies a read/write deadline to."""
    tails: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)):
            continue
        if node.func.attr == "settimeout" and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and arg.value is None:
                continue  # explicit blocking mode is not a deadline
            t = _tail(node.func.value)
            if t is not None:
                tails.add(t)
        elif node.func.attr == "setsockopt":
            names = {
                n.attr if isinstance(n, ast.Attribute) else n.id
                for a in node.args for n in ast.walk(a)
                if isinstance(n, (ast.Attribute, ast.Name))
            }
            if names & _TIMEOUT_OPTS:
                t = _tail(node.func.value)
                if t is not None:
                    tails.add(t)
    return tails


def run(ctx: ModuleContext) -> Iterator[Finding]:
    if not ctx.rel.startswith(_SCOPE_PREFIX):
        return
    deadlined = _deadlined_tails(ctx)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = _tail(fn)
        if name in ("recv", "recv_into") and isinstance(fn, ast.Attribute):
            if any(ctx.rel.endswith(suffix)
                   and ctx.scope_of(node) == scope
                   for suffix, scope in _RECV_PRIMITIVES):
                continue
            yield ctx.finding(
                CHECK, node,
                f"raw socket .{name}() outside the sanctioned wire "
                "read primitives (wire._recv_exact / ws._read_exact) "
                "— read through wire.recv_msg on a deadlined socket "
                "instead",
            )
        elif name == "create_connection":
            if len(node.args) >= 2 or any(
                kw.arg == "timeout" for kw in node.keywords
            ):
                continue
            yield ctx.finding(
                CHECK, node,
                "create_connection without a timeout — a wedged or "
                "blackholed server would hang the dialing thread "
                "forever",
            )
        elif name == "connect" and isinstance(fn, ast.Attribute):
            if _tail(fn.value) in deadlined:
                continue
            yield ctx.finding(
                CHECK, node,
                "socket .connect() with no deadline applied to "
                f"'{_tail(fn.value)}' anywhere in this module — use "
                "create_connection(timeout=...) or settimeout first",
            )
        elif name in ("recv_msg", "recv_frame") and node.args:
            if ctx.rel.endswith("distributed/wire.py"):
                continue  # the wire plane's own internal plumbing
            target = _tail(node.args[0])
            if target in deadlined:
                continue
            yield ctx.finding(
                CHECK, node,
                f"wire.{name} on '{target}' but this module never "
                "applies a read deadline to that socket (settimeout / "
                "SO_RCVTIMEO) — a dead peer would block this thread "
                "unboundedly",
            )
