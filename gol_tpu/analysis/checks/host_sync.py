"""host-sync — host-device synchronization inside traced hot paths.

A `.item()`, `float()`/`int()`/`bool()` of a traced value, or an
`np.asarray`/`np.array` call inside a jitted function either fails at
trace time or (worse) silently forces a device round trip per call —
the exact tax the fused-chunk and diff-stack paths exist to avoid
(docs/PERF.md). `block_until_ready` is flagged anywhere outside bench
code: in the engine plane it serializes the dispatch pipeline, which is
only ever intentional (and then allowlisted with the reason).
"""

from __future__ import annotations

import ast
from typing import Iterator, Set

from gol_tpu.analysis.core import (
    Finding,
    ModuleContext,
    dynamic_names,
    traced_params,
)

CHECK = "host-sync"

#: numpy-namespace calls that materialize a host array from their arg.
_HOST_MATERIALIZERS = {"asarray", "array", "ascontiguousarray"}
#: Python builtins that force a scalar read-back of a traced value.
_SCALARIZERS = {"float", "int", "bool"}
#: Paths where blocking on the device is the point, not a hazard.
_BENCH_PATH_TOKENS = ("bench", "scripts/", "tests/", "__graft_entry__")


def _numpy_roots(ctx: ModuleContext) -> Set[str]:
    """Names the module binds to the real numpy ('np', 'numpy', ...) —
    jnp.asarray under trace is fine; np.asarray is the sync."""
    roots = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "numpy":
                    roots.add(a.asname or "numpy")
        elif isinstance(node, ast.ImportFrom):
            if node.module == "numpy":
                continue  # from numpy import x — rare, skip
    return roots or {"np", "numpy", "_np"}


def run(ctx: ModuleContext) -> Iterator[Finding]:
    numpy_roots = _numpy_roots(ctx)
    bench_path = any(tok in ctx.rel for tok in _BENCH_PATH_TOKENS)
    for node in ast.walk(ctx.tree):
        # block_until_ready outside bench code — module-wide, traced
        # or not (on the host side it stalls the dispatch pipeline).
        if (not bench_path and isinstance(node, ast.Attribute)
                and node.attr == "block_until_ready"):
            yield ctx.finding(
                CHECK, node,
                "block_until_ready outside bench code serializes the "
                "dispatch pipeline (allowlist only with the reason it "
                "is intentional)",
            )
            continue
        if not isinstance(node, ast.Call):
            continue
        info = ctx.jit_context(node)
        if info is None:
            continue
        traced = traced_params(info)
        callee = node.func
        # x.item() under trace: concretization error / forced sync.
        if isinstance(callee, ast.Attribute) and callee.attr == "item" \
                and not node.args:
            yield ctx.finding(
                CHECK, node,
                f".item() inside traced '{info.qualname}' forces a "
                "host read-back of a device value",
            )
        # np.asarray(...) & friends under trace.
        elif isinstance(callee, ast.Attribute) \
                and callee.attr in _HOST_MATERIALIZERS \
                and isinstance(callee.value, ast.Name) \
                and callee.value.id in numpy_roots:
            yield ctx.finding(
                CHECK, node,
                f"np.{callee.attr}() inside traced '{info.qualname}' "
                "materializes a host array from a traced value",
            )
        # float(x)/int(x)/bool(x) where x mentions a traced param as a
        # VALUE — int(w.shape[0]) reads static metadata and is free,
        # which dynamic_names exempts (same vocabulary as the
        # tracer-branch check).
        elif isinstance(callee, ast.Name) and callee.id in _SCALARIZERS \
                and node.args:
            hit = dynamic_names(node.args[0]) & traced
            if hit:
                yield ctx.finding(
                    CHECK, node,
                    f"{callee.id}() of traced value "
                    f"'{sorted(hit)[0]}' inside '{info.qualname}' "
                    "forces a host scalar read-back",
                )
