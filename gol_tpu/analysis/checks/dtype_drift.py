"""dtype-drift — off-contract dtypes in the kernel plane.

Every kernel family in this repo speaks exactly four dtypes: uint8
boards ({0,255} cells / gray levels), uint32 packed words (SWAR rows,
diff bitmaps), int32 counts/indices/bitcast rows, and bool masks. The
packed and dense families stay bit-exact against each other (the
cross-backend tests) precisely because nothing ever routes through a
float or a differently-sized integer — a float32 neighbour sum or an
int16 index sneaking into `ops/bitlife.py` or `parallel/packed_halo.py`
is drift between the families even when it happens to round-trip.

The check walks dtype references (`jnp.float32`, `dtype="float64"`,
`.astype('int16')`) in kernel modules — selected by filename stem, so
the families cannot drift by adding a new kernel file either — and
flags any dtype outside the contract set.
"""

from __future__ import annotations

import ast
import pathlib
import re
from typing import Iterator

from gol_tpu.analysis.core import Finding, ModuleContext

CHECK = "dtype-drift"

#: The kernel plane's entire dtype vocabulary (see module docstring).
KERNEL_DTYPES = {"uint8", "uint32", "int32", "bool_", "bool"}

#: Dtype tokens worth flagging when seen outside the contract set.
_ALL_DTYPES = {
    "uint8", "uint16", "uint32", "uint64",
    "int8", "int16", "int32", "int64",
    "float16", "float32", "float64", "bfloat16",
    "complex64", "complex128", "bool_", "bool",
}

#: Kernel modules by filename stem: the ops/ families and the ring
#: steppers. (multihost/board/wire host plumbing legitimately uses
#: int64 and is not kernel code.)
_KERNEL_STEM = re.compile(
    r"(^|_)(bit\w*|pallas\w*|halo|life|gens|generations|stepper)$"
)


def is_kernel_module(rel: str) -> bool:
    return bool(_KERNEL_STEM.search(pathlib.PurePosixPath(rel).stem))


def run(ctx: ModuleContext) -> Iterator[Finding]:
    if not is_kernel_module(ctx.rel):
        return
    for node in ast.walk(ctx.tree):
        token = None
        if isinstance(node, ast.Attribute) and node.attr in _ALL_DTYPES:
            token = node.attr
        elif isinstance(node, ast.Call):
            # dtype="float32" kwarg / .astype("float32") string form.
            cands = [k.value for k in node.keywords if k.arg == "dtype"]
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("astype", "view")):
                cands.extend(node.args[:1])
            for c in cands:
                if isinstance(c, ast.Constant) and c.value in _ALL_DTYPES:
                    token = c.value
        if token is not None and token not in KERNEL_DTYPES:
            yield ctx.finding(
                CHECK, node,
                f"dtype '{token}' in kernel module — the packed/dense "
                f"kernel contract is exactly {sorted(KERNEL_DTYPES - {'bool'})}",
            )
