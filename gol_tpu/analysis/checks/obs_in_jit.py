"""obs-in-jit — metrics/span/flight calls inside traced functions.

The gol_tpu.obs contract is explicit: instrumentation is HOST-SIDE, at
dispatch/event granularity, never inside a jit/pallas trace. A metric
call under trace would either be baked in as a once-per-compile no-op
(silently recording nothing per step — the worst kind of broken
observability) or force a host callback per traced op. The same holds
for the span tracer and the flight recorder (gol_tpu.obs.tracing /
.flight): a span enter/exit or a black-box note under trace records
once per COMPILE — a timeline that silently shows nothing. This check
makes the contract machine-enforced: any call that reaches the
registry, the tracer, or the recorder — through the `obs` module
object, a name imported from any gol_tpu.obs module, or a module-level
handle assigned from one — is flagged when it sits in a jit context
(decorated defs, scan/shard_map/fori_loop bodies, jitted lambdas — the
same discovery every other check uses).
"""

from __future__ import annotations

import ast
from typing import Iterator, Set

from gol_tpu.analysis.core import Finding, ModuleContext

CHECK = "obs-in-jit"

#: The observability plane's modules — a name imported FROM any of
#: these (or binding one) becomes a tainted root, so calls through it
#: under trace are flagged; plain `.inc()` on an unrelated object never
#: fires. tracing/flight joined in r7: span enter/exit and
#: flight-recorder appends are as host-side-only as metric mutations.
_OBS_MODULES = (
    "gol_tpu.obs",
    "gol_tpu.obs.registry",
    "gol_tpu.obs.http",
    "gol_tpu.obs.tracing",
    "gol_tpu.obs.flight",
    "gol_tpu.obs.device",
    "gol_tpu.obs.console",
    # PR 17: metering is host-side at dispatch/event granularity —
    # a charge() inside a traced function would bake one Python-time
    # sample into the compiled program.
    "gol_tpu.obs.accounting",
)


def _target_roots(tgt: ast.AST) -> Iterator[str]:
    """Root names an assignment target binds/mutates: `x` -> x,
    `x[k] = ...` / `x.attr = ...` -> x, tuple targets recurse. `self`/
    `cls` attribute targets are EXCLUDED — an instance holding a metric
    handle is handled at class granularity (see _obs_bound_names), and
    tainting the literal name 'self' would flag every `self.anything()`
    call in the module's traced methods (a verified false positive)."""
    if isinstance(tgt, ast.Name):
        yield tgt.id
    elif isinstance(tgt, (ast.Attribute, ast.Subscript)):
        root = _root_name(tgt)
        if root is not None and root not in ("self", "cls"):
            yield root
    elif isinstance(tgt, (ast.Tuple, ast.List)):
        for elt in tgt.elts:
            yield from _target_roots(elt)


def _obs_bound_names(ctx: ModuleContext) -> Set[str]:
    """Names this module binds to gol_tpu.obs or to things derived from
    it: the module alias itself, `from gol_tpu.obs import X` names,
    classes whose bodies touch an obs root (handle containers like the
    `_EngineMetrics` pattern — their constructors and instances carry
    metric handles), and assignment targets whose value expression is
    rooted at any of those (`_M = obs.counter(...)`,
    `_METRICS = _EngineMetrics()`, dict-fills of handles)."""
    roots: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name in _OBS_MODULES:
                    # `import gol_tpu.obs` binds `gol_tpu`;
                    # `import gol_tpu.obs as obs` binds the alias.
                    roots.add(a.asname or a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod in _OBS_MODULES:
                for a in node.names:
                    roots.add(a.asname or a.name)
            elif mod == "gol_tpu":
                for a in node.names:
                    if a.name == "obs":
                        roots.add(a.asname or "obs")
    if not roots:
        return roots
    # Propagate until fixed point: classes whose body touches an obs
    # root become roots themselves (instances are handle containers),
    # and assignment targets inherit rootness from their value.
    changed = True
    while changed:
        changed = False
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                if node.name not in roots and _mentions(node, roots):
                    roots.add(node.name)
                    changed = True
            elif isinstance(node, ast.Assign):
                if not _mentions(node.value, roots):
                    continue
                for tgt in node.targets:
                    for name in _target_roots(tgt):
                        if name not in roots:
                            roots.add(name)
                            changed = True
    return roots


def _mentions(expr: ast.AST, names: Set[str]) -> bool:
    return any(
        isinstance(n, ast.Name) and n.id in names for n in ast.walk(expr)
    )


def _root_name(node: ast.AST):
    """Leftmost Name of a dotted/subscripted access chain."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def run(ctx: ModuleContext) -> Iterator[Finding]:
    roots = _obs_bound_names(ctx)
    if not roots:
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        info = ctx.jit_context(node)
        if info is None:
            continue
        root = _root_name(node.func)
        if root in roots:
            yield ctx.finding(
                CHECK, node,
                f"metrics call rooted at obs-bound name '{root}' inside "
                f"traced '{info.qualname}' — instrumentation must stay "
                "host-side (a traced metric records once per COMPILE, "
                "not per step)",
            )
