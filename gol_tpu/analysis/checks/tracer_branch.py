"""tracer-branch — Python control flow on traced values.

An `if`/`while` whose condition mentions a traced (non-static) argument
of a jitted function raises ConcretizationTypeError at trace time — or,
when the value happens to be weakly-typed, silently bakes one branch
into the compiled program. Data-dependent branching belongs in
`lax.cond`/`lax.select`/`jnp.where`; Python branching is only legal on
static arguments, which the check exempts via static_argnames.
"""

from __future__ import annotations

import ast
from typing import Iterator

from gol_tpu.analysis.core import (
    Finding,
    ModuleContext,
    dynamic_names,
    traced_params,
)

CHECK = "tracer-branch"


def run(ctx: ModuleContext) -> Iterator[Finding]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.If, ast.While)):
            continue
        info = ctx.jit_context(node)
        if info is None:
            continue
        traced = traced_params(info)
        hit = sorted(dynamic_names(node.test) & traced)
        if hit:
            kind = "if" if isinstance(node, ast.If) else "while"
            yield ctx.finding(
                CHECK, node,
                f"Python '{kind}' on traced value '{hit[0]}' inside "
                f"'{info.qualname}' — use lax.cond/jnp.where, or mark "
                "the argument static",
            )
