"""Machine-checked guardrails for the codebase's two failure planes.

The repo's correctness rests on invariants nothing used to check: JAX
hazards that silently erase perf wins (host syncs inside jitted hot
paths, per-call recompiles, Python control flow on tracers, dtype drift
between the packed and dense kernel families, donation decisions on the
ring steppers' carried state), and distributed protocol orderings the
server and SPMD mirror merely assumed (FlipBatch/TurnComplete
adjacency, no flips across a BoardSync, monotone turns, sparse-redo
dispatch identity). This package makes both machine-checked:

- `jaxlint` + `checks/`: a pure-AST static linter over the package
  (`python -m gol_tpu.analysis`, tier-1 via tests/test_analysis.py).
  Pre-existing findings live in `allowlist.txt` WITH a reason each;
  new hazards fail CI, and `scripts/check_analysis.sh` keeps the
  allowlist shrink-only.
- `invariants`: a runtime event-stream / dispatch-order monitor wired
  into the engine server's broadcaster and the stepper dispatch chain
  behind the `GOL_TPU_CHECK_INVARIANTS` opt-in (cli `--check-invariants`),
  and turned on in the test suite.

The linter imports neither jax nor the package it lints — it must run
(and fail usefully) even when the code under analysis cannot import.
"""

from gol_tpu.analysis.core import Allowlist, Finding
from gol_tpu.analysis.jaxlint import lint_paths
from gol_tpu.analysis.invariants import (
    DispatchLinearityChecker,
    EventStreamChecker,
    InvariantViolation,
    checked_stepper,
    invariants_enabled,
)

__all__ = [
    "Allowlist",
    "DispatchLinearityChecker",
    "EventStreamChecker",
    "Finding",
    "InvariantViolation",
    "checked_stepper",
    "invariants_enabled",
    "lint_paths",
]
