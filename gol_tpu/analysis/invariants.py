"""Runtime invariant checker — the protocol orderings the distributed
plane ASSUMES, asserted.

Two monitors, both cheap enough to leave on in tests and opt into in
production via `GOL_TPU_CHECK_INVARIANTS=1` (cli: `--check-invariants`):

- `EventStreamChecker` watches one engine event stream (the server's
  broadcaster wraps its loop with it) and asserts:
    * FlipBatch/TurnComplete adjacency: flips for turn t are flushed by
      TurnComplete(t) before anything else claims the stream position —
      the ordering distributed/server.py's per-peer flush relies on;
    * no flips buffered across a BoardSync: a sync supersedes any
      batched diff, so an unflushed FlipBatch crossing one would be
      double-applied by XOR consumers (ADVICE #1's corruption mode);
    * monotone committed turns: TurnComplete strictly increases, and no
      FlipBatch/BoardSync rewinds behind the stream position (a stale
      event is a reordering bug upstream, not a display glitch).
- `DispatchLinearityChecker` (via `checked_stepper`) wraps a Stepper
  and asserts the SPMD dispatch contract spmd_stepper documents: every
  dispatch consumes a world a previous dispatch produced, and the
  sparse-overflow redo consumes exactly the sparse call's input — the
  invariant that keeps coordinator and workers stepping the same ring
  state (ADVICE #2's divergence mode).

Violations raise `InvariantViolation` (an AssertionError subclass, so
plain `pytest.raises(AssertionError)` and `assert`-oriented tooling see
them) with a message naming the event/dispatch and both turns involved.

Every violation ALSO increments `gol_tpu_invariant_violations_total`
(labelled by checker) in the process-global metrics registry
(gol_tpu.obs) before raising — so a live `/metrics` endpoint shows a
violation even when the raising thread's traceback only lands in a log,
and `tests/test_distributed.py` fails loudly on any nonzero delta.

This module imports neither jax nor the engine (gol_tpu.obs is pure
stdlib): it must be importable from the linter CLI and from worker
processes at zero cost.
"""

from __future__ import annotations

import os
import weakref
from collections import deque
from typing import Optional

from gol_tpu import obs

__all__ = [
    "DispatchLinearityChecker",
    "EventStreamChecker",
    "InvariantViolation",
    "checked_stepper",
    "enable",
    "invariants_enabled",
    "violations_total",
]

_VIOLATIONS = {
    kind: obs.counter(
        "gol_tpu_invariant_violations_total",
        "Distributed-protocol invariant violations observed at runtime",
        {"checker": kind},
    ) for kind in ("event-stream", "dispatch-linearity")
}


def violations_total() -> int:
    """Total runtime invariant violations this process has observed —
    the number that must stay 0 across any healthy run (tests assert
    the per-test delta)."""
    return int(sum(c.value for c in _VIOLATIONS.values()))


def _flight_note(checker: str, msg: str) -> None:
    """A violation is flight-recorder material: the black box must
    show protocol breaches in the window before a crash, even when the
    raising thread's traceback only lands in a log."""
    from gol_tpu.obs import flight

    flight.note("invariant.violation", checker=checker, msg=msg)


class InvariantViolation(AssertionError):
    """A distributed-protocol invariant was observed broken."""


def invariants_enabled() -> bool:
    return os.environ.get("GOL_TPU_CHECK_INVARIANTS", "") == "1"


def enable(on: bool = True) -> None:
    """Programmatic switch (the CLI flag and tests use this); the env
    var form is what multi-process jobs inherit."""
    if on:
        os.environ["GOL_TPU_CHECK_INVARIANTS"] = "1"
    else:
        os.environ.pop("GOL_TPU_CHECK_INVARIANTS", None)


class EventStreamChecker:
    """Assert stream-order invariants over one engine event stream.

    `observe(ev)` every event in delivery order; raises
    InvariantViolation on the first breach. Type dispatch is by class
    name so the checker needs no import of gol_tpu.events (and so
    wire-decoded peer-side event objects check the same way)."""

    def __init__(self, source: str = "engine"):
        self.source = source
        self._pending_turn: Optional[int] = None  # unflushed FlipBatch
        self._pending_initial = False  # the pre-loop alive burst
        self._last_tc: Optional[int] = None
        self._sync_turn: Optional[int] = None
        self.observed = 0

    def _fail(self, msg: str) -> None:
        _VIOLATIONS["event-stream"].inc()
        _flight_note("event-stream", f"[{self.source}] {msg}")
        raise InvariantViolation(f"[{self.source}] {msg}")

    def observe(self, ev) -> None:
        self.observed += 1
        kind = type(ev).__name__
        turn = getattr(ev, "completed_turns", None)
        if kind in ("FlipBatch", "CellFlipped"):
            self._on_flips(turn, kind)
        elif kind == "FlipChunk":
            self._on_flip_chunk(getattr(ev, "first_turn", None), turn)
        elif kind == "TurnComplete":
            self._on_turn_complete(turn)
        elif kind == "BoardSync":
            self._on_board_sync(turn)
        elif kind == "FinalTurnComplete":
            if self._last_tc is not None and turn < self._last_tc:
                self._fail(
                    f"FinalTurnComplete at turn {turn} behind the last "
                    f"TurnComplete ({self._last_tc}) — stale final event"
                )

    def _on_flips(self, turn: int, kind: str) -> None:
        if self._sync_turn is not None and turn <= self._sync_turn:
            self._fail(
                f"{kind} for turn {turn} after a BoardSync at turn "
                f"{self._sync_turn} — those flips are already in the "
                "synced board and would be double-applied"
            )
        if self._last_tc is not None and turn <= self._last_tc:
            self._fail(
                f"stale {kind} for turn {turn}: the stream is already "
                f"at TurnComplete {self._last_tc}"
            )
        if self._pending_turn is not None and turn != self._pending_turn:
            if not self._pending_initial:
                self._fail(
                    f"{kind} for turn {turn} while flips for turn "
                    f"{self._pending_turn} are unflushed (no "
                    f"TurnComplete {self._pending_turn} arrived) — the "
                    "older batch would be lost or mis-applied"
                )
        if self._pending_turn is None:
            # The engine's initial alive burst precedes the turn loop
            # and owes no TurnComplete; only the very first batch of a
            # stream (before any TurnComplete) gets that license.
            self._pending_initial = self._last_tc is None
        elif turn != self._pending_turn:
            self._pending_initial = False
        self._pending_turn = turn

    def _on_flip_chunk(self, first_turn, last_turn: int) -> None:
        """A FlipChunk is k (FlipBatch, TurnComplete) pairs emitted
        atomically: it must start exactly one turn past the stream
        position, never rewind behind a sync, and it advances the
        stream to its last turn (so a chunk can never straddle a
        BoardSync — the engine only emits whole chunks between
        dispatch boundaries, where syncs are serviced)."""
        if first_turn is None or last_turn < first_turn:
            self._fail(
                f"malformed FlipChunk: turns {first_turn}..{last_turn}"
            )
        if self._sync_turn is not None and first_turn <= self._sync_turn:
            self._fail(
                f"FlipChunk starting at turn {first_turn} after a "
                f"BoardSync at turn {self._sync_turn} — its leading "
                "turns are already in the synced board"
            )
        if self._last_tc is not None and first_turn <= self._last_tc:
            self._fail(
                f"stale FlipChunk starting at turn {first_turn}: the "
                f"stream is already at TurnComplete {self._last_tc}"
            )
        if self._pending_turn is not None and not self._pending_initial:
            self._fail(
                f"FlipChunk at turns {first_turn}..{last_turn} while "
                f"flips for turn {self._pending_turn} are unflushed"
            )
        self._last_tc = last_turn
        self._pending_turn = None
        self._pending_initial = False

    def _on_turn_complete(self, turn: int) -> None:
        if self._last_tc is not None and turn <= self._last_tc:
            self._fail(
                f"non-monotone TurnComplete: turn {turn} after turn "
                f"{self._last_tc}"
            )
        if self._pending_turn is not None and not self._pending_initial \
                and turn != self._pending_turn:
            self._fail(
                f"TurnComplete {turn} does not flush the pending "
                f"FlipBatch for turn {self._pending_turn} — the "
                "FlipBatch/TurnComplete adjacency the broadcaster "
                "relies on is broken"
            )
        self._last_tc = turn
        self._pending_turn = None
        self._pending_initial = False

    def _on_board_sync(self, turn: int) -> None:
        if self._pending_turn is not None and not self._pending_initial:
            self._fail(
                f"BoardSync at turn {turn} while flips for turn "
                f"{self._pending_turn} are buffered — flips must never "
                "straddle a sync (the sync supersedes them)"
            )
        if self._last_tc is not None and turn < self._last_tc:
            self._fail(
                f"stale BoardSync for turn {turn} behind TurnComplete "
                f"{self._last_tc} — a rewound sync would corrupt every "
                "synced peer"
            )
        self._sync_turn = turn
        self._pending_turn = None
        self._pending_initial = False


def _maybe_weak(obj):
    """Weak reference when the type allows it (jax Arrays do), else a
    trivial strong closure (plain numpy arrays in host-only steppers
    don't). Weak on purpose: the checker must observe the dispatch
    chain WITHOUT pinning board-sized device buffers the engine has
    already released — several extra live boards would be a real
    memory cost on budget-sized runs, not the advertised free opt-in."""
    try:
        return weakref.ref(obj)
    except TypeError:
        return lambda: obj


class DispatchLinearityChecker:
    """Assert the stepper dispatch contract: each dispatch consumes a
    world a recent dispatch produced (`put` seeds the chain; the
    pipelined diff path legitimately runs one chunk ahead, so a short
    window of recent outputs is live, not just the newest), and the
    sparse-overflow redo consumes exactly an OUTSTANDING sparse call's
    input. Identity checks through weak references only — nothing
    touches the device and nothing is kept alive by the checker.

    A sparse dispatch's redo window closes two NON-REDO dispatches
    later: the engine consumes chunks in order and chunk N's truncation
    redo always lands before chunk N+2's consume — at most one forward
    dispatch (the pipelined lookahead) can intervene. Redo dispatches
    themselves don't age the window: a burst under the pipelined path
    legitimately redoes chunks N and N+1 back to back (the stale-cap
    double redo distributor._diff_dispatch documents), and counting the
    first redo would retire the second chunk's window early and kill a
    bit-correct run. Beyond that window, a redo against an older sparse
    input is a re-step of already-committed turns and is rejected (the
    false negative a consume-blind checker would let through)."""

    #: Outputs considered live: the current world plus the pipelined
    #: path's one-chunk lookahead (and its redo continuation).
    WINDOW = 4
    #: Non-redo dispatches after which a sparse redo window is closed.
    SPARSE_WINDOW = 2

    def __init__(self, name: str = "stepper"):
        self.name = name
        self._live: deque = deque(maxlen=self.WINDOW)  # weakrefs
        # Outstanding sparse rows: (seq, input_ref, output_ref). The
        # pipelined diff path dispatches one chunk ahead, so TWO sparse
        # chunks can be in flight when the older one turns out
        # truncated — a single slot would false-flag the older redo.
        self._sparse: deque = deque(maxlen=self.WINDOW)
        self._seq = 0

    def _fail(self, msg: str) -> None:
        _VIOLATIONS["dispatch-linearity"].inc()
        _flight_note("dispatch-linearity", f"[{self.name}] {msg}")
        raise InvariantViolation(f"[{self.name}] {msg}")

    def put(self, world) -> None:
        self._live.clear()
        self._live.append(_maybe_weak(world))
        self._sparse.clear()

    def _advance(self, out, redo: bool = False) -> None:
        if not redo:
            self._seq += 1
        if out is not None:
            self._live.append(_maybe_weak(out))
        # Retire sparse pairs whose redo window has closed (or whose
        # input the engine already dropped — a dead ref can never be
        # legally redone).
        while self._sparse and (
            self._sparse[0][0] <= self._seq - self.SPARSE_WINDOW
            or self._sparse[0][1]() is None
        ):
            self._sparse.popleft()

    def dispatch(self, world, out, what: str) -> None:
        """A linear dispatch consuming `world`, producing `out`."""
        live = [r() for r in self._live]
        if any(w is not None for w in live) and all(
                world is not w for w in live if w is not None):
            self._fail(
                f"{what} dispatched on a world no recent dispatch "
                f"produced (id {id(world):#x} not among recent outputs "
                f"{[hex(id(w)) for w in live if w is not None]}) — "
                "coordinator and workers would step divergent ring state"
            )
        self._advance(out)

    def sparse(self, world, out) -> None:
        self.dispatch(world, out, "sparse diff scan")
        self._sparse.append((self._seq, _maybe_weak(world),
                             _maybe_weak(out)))

    def redo(self, world) -> None:
        if not self._sparse:
            self._fail(
                "dense redo dispatched with no sparse scan outstanding"
            )
        for entry in self._sparse:
            if world is entry[1]():
                self._sparse.remove(entry)
                self._advance(None, redo=True)
                return
        self._fail(
            "dense redo must re-step an outstanding sparse scan's exact "
            f"input (got id {id(world):#x}, outstanding inputs "
            f"{[hex(id(e[1]())) for e in self._sparse]})"
        )


def checked_stepper(stepper, name: Optional[str] = None):
    """Wrap a Stepper's dispatch entries with a DispatchLinearityChecker
    (dataclasses.replace, so any Stepper-shaped dataclass works; no
    import of parallel.stepper — this module stays engine-free)."""
    import dataclasses

    chk = DispatchLinearityChecker(name or f"checked-{stepper.name}")
    inner_redo = stepper.step_n_with_diffs_redo or stepper.step_n_with_diffs

    def put(world):
        out = stepper.put(world)
        chk.put(out)
        return out

    def step(world):
        out = stepper.step(world)
        chk.dispatch(world, out, "step")
        return out

    def step_n(world, k):
        out = stepper.step_n(world, k)
        chk.dispatch(world, out[0], "step_n")
        return out

    def step_with_diff(world):
        out = stepper.step_with_diff(world)
        chk.dispatch(world, out[0], "step_with_diff")
        return out

    step_n_with_diffs = None
    if stepper.step_n_with_diffs is not None:
        def step_n_with_diffs(world, k):
            out = stepper.step_n_with_diffs(world, k)
            chk.dispatch(world, out[0], "step_n_with_diffs")
            return out

    step_n_with_diffs_redo = None
    if inner_redo is not None:
        def step_n_with_diffs_redo(world, k):
            chk.redo(world)
            out = inner_redo(world, k)
            chk._live.append(_maybe_weak(out[0]))
            return out

    step_n_with_diffs_sparse = None
    if stepper.step_n_with_diffs_sparse is not None:
        def step_n_with_diffs_sparse(world, k, cap):
            out = stepper.step_n_with_diffs_sparse(world, k, cap)
            chk.sparse(world, out[0])
            return out

    step_n_with_diffs_compact = None
    if stepper.step_n_with_diffs_compact is not None:
        def step_n_with_diffs_compact(world, k, total_cap):
            # Compact chunks carry the same overflow-redo contract as
            # sparse rows (the redo must re-step this exact input), so
            # they register in the same outstanding window.
            out = stepper.step_n_with_diffs_compact(world, k, total_cap)
            chk.sparse(world, out[0])
            return out

    wrapped = dataclasses.replace(
        stepper,
        name=f"checked-{stepper.name}",
        put=put,
        step=step,
        step_n=step_n,
        step_with_diff=step_with_diff,
        step_n_with_diffs=step_n_with_diffs,
        step_n_with_diffs_redo=step_n_with_diffs_redo,
        step_n_with_diffs_sparse=step_n_with_diffs_sparse,
        step_n_with_diffs_compact=step_n_with_diffs_compact,
    )
    wrapped.checker = chk
    return wrapped
