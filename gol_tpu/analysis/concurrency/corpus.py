"""Corpus runner — prove the concurrency passes flag the shipped bugs.

`tests/fixtures/concurrency/` re-encodes each historically-fixed race
from CHANGES.md (the PR 12 detach deadlock, the PR 7 attach-under-
conn-lock, the writer-pool peek-then-pop, the WS gauge double
decrement, the heartbeat verb starvation) as a minimal module whose
first line declares what the analyzer MUST say about it:

    # lint-expect: lock-order[, lock-blocking, ...]

This runner stages every fixture into a `gol_tpu/`-shaped temp tree
(the checks are path-scoped to the serving plane), lints it with the
concurrency checks only, and fails if any declared check does not fire
on its file — the analyzer regression-tested against the bug classes
this codebase actually shipped. `scripts/check_analysis.sh` runs it
next to the strict gate; `tests/test_analysis_concurrency.py` runs the
same entry in-process.

    python -m gol_tpu.analysis.concurrency.corpus [fixture_dir]
"""

from __future__ import annotations

import pathlib
import re
import shutil
import sys
import tempfile
from typing import Dict, List, Set, Tuple

_EXPECT_RE = re.compile(r"^#\s*lint-expect:\s*(?P<checks>[\w, -]+)\s*$")
_DEFAULT_DIR = "tests/fixtures/concurrency"
#: Where fixtures are staged — inside the checks' serving-plane scope.
_STAGE = "gol_tpu/distributed"


def expected_checks(source: str) -> Set[str]:
    """The checks a fixture's `# lint-expect:` header declares."""
    for line in source.splitlines()[:5]:
        m = _EXPECT_RE.match(line.strip())
        if m:
            return {c.strip() for c in m.group("checks").split(",")
                    if c.strip()}
    return set()


def run_corpus(fixture_dir: pathlib.Path
               ) -> Tuple[List[str], Dict[str, Set[str]]]:
    """(failures, {fixture name: checks that fired}). A fixture with no
    lint-expect header is itself a failure — an undeclared corpus file
    proves nothing."""
    from gol_tpu.analysis.concurrency import CONCURRENCY_CHECKS
    from gol_tpu.analysis.jaxlint import lint_paths

    fixtures = sorted(fixture_dir.glob("*.py"))
    failures: List[str] = []
    fired: Dict[str, Set[str]] = {}
    if not fixtures:
        return [f"no corpus fixtures under {fixture_dir}"], fired
    with tempfile.TemporaryDirectory(prefix="gol-corpus-") as td:
        root = pathlib.Path(td)
        stage = root / _STAGE
        stage.mkdir(parents=True)
        expect: Dict[str, Set[str]] = {}
        for f in fixtures:
            expect[f.name] = expected_checks(f.read_text())
            if not expect[f.name]:
                failures.append(f"{f.name}: missing '# lint-expect:' header")
            shutil.copy(f, stage / f.name)
        findings = lint_paths([root / "gol_tpu"], root,
                              checks=CONCURRENCY_CHECKS)
        for fd in findings:
            fired.setdefault(pathlib.Path(fd.path).name, set()).add(fd.check)
        for name, want in expect.items():
            missing = want - fired.get(name, set())
            if missing:
                failures.append(
                    f"{name}: expected {sorted(missing)} to fire, got "
                    f"{sorted(fired.get(name, set())) or 'nothing'}")
    return failures, fired


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    fixture_dir = pathlib.Path(args[0] if args else _DEFAULT_DIR)
    if not fixture_dir.is_dir():
        print(f"corpus: no such fixture dir {fixture_dir}", file=sys.stderr)
        return 2
    failures, fired = run_corpus(fixture_dir)
    for name in sorted(fired):
        print(f"corpus: {name}: {', '.join(sorted(fired[name]))}")
    if failures:
        for f in failures:
            print(f"corpus FAIL: {f}", file=sys.stderr)
        return 1
    print(f"corpus: {len(fired)} fixture(s), every declared check fired")
    return 0


if __name__ == "__main__":
    sys.exit(main())
