"""lock-blocking — locks held across operations that block the thread.

A lock held across a blocking call turns one slow peer into a stalled
plane: every thread that wants the lock waits out the blocked one's
socket deadline (the PR 7 shape — `_conn_lock` held across
`manager.attach`, which can sit behind a cold bucket compile, starved
the heartbeat judge into evicting live peers). Flagged here:

- a blocking operation (socket send/recv/connect/accept, wire frame
  I/O, `block_until_ready`, `time.sleep`, event/condition waits,
  thread joins, deadlined queue ops) lexically inside a `with <lock>:`
  body, and
- a call made while holding a lock whose resolved callee can block,
  transitively through the project call graph — `manager.attach`
  blocks because `_exec` waits on the engine thread, which is invisible
  to any single-file pass.

The legitimate exceptions are locks whose entire PURPOSE is to
serialize one socket (`_Conn._lock` around `sendall` — the wire is the
resource the lock guards, and the writer deadline bounds the hold).
Those carry allowlist entries with that retained-contract rationale,
the same discipline the donation check uses; an entry here is a
documented design decision, not a mute button.
"""

from __future__ import annotations

from typing import Iterator, Sequence

from gol_tpu.analysis.core import Finding, ModuleContext
from gol_tpu.analysis.concurrency.graph import blocking_op, index_for

CHECK = "lock-blocking"

SCOPE_PREFIX = ("gol_tpu/distributed/", "gol_tpu/relay/",
                "gol_tpu/sessions/", "gol_tpu/replay/", "gol_tpu/engine/",
                # PR 17: the accounting plane's contract is that ledger
                # file I/O never runs under a lock the serving path
                # takes — the meter's lock only guards dict updates.
                "gol_tpu/obs/accounting")


def run_project(ctxs: Sequence[ModuleContext]) -> Iterator[Finding]:
    index = index_for(ctxs)
    for fn in index.funcs:
        if not fn.rel.startswith(SCOPE_PREFIX):
            continue
        for op in fn.blocking:
            if not op.held:
                continue
            yield fn.ctx.finding(
                CHECK, op.node,
                f"{op.desc} while holding {', '.join(op.held)} — every "
                "thread wanting that lock now waits out this I/O; move "
                "the blocking work outside the lock or document the "
                "lock-serializes-this-resource contract in the "
                "allowlist",
            )
        for cs in fn.calls:
            if not cs.held or blocking_op(cs.node) is not None:
                continue  # direct ops already flagged above
            for target in cs.targets:
                why = index.blocking_reason(target)
                if why is None:
                    continue
                yield fn.ctx.finding(
                    CHECK, cs.node,
                    f"call to {target.qualname} while holding "
                    f"{', '.join(cs.held)}, and {target.qualname} can "
                    f"block: {why} — the PR 7 attach-under-conn-lock "
                    "shape; call it after releasing the lock",
                )
                break
