"""thread-ownership — the declared thread-ownership table, enforced.

The serving plane's thread roles are a contract the code only states
in comments ("peek_turn, NOT manager.get: the manager lock is held
across bucket dispatches"). This check turns the contract into data.
The table (docs/ANALYSIS.md reproduces it):

- **Outbound frames are writer-plane-only.** Raw `sendall` /
  `wire.send_frame` may appear only in the sanctioned writer scopes:
  the wire primitives themselves, `_Conn`'s serialized send paths
  (`_send_now` / `_write_loop` / `send_direct`), the WS control
  senders (`WSConn.beacon` / `enqueue_control`), and the relay's
  reject/handshake paths. Everything else must enqueue through a
  `_Conn`/pool so backpressure accounting and shed policy see the
  frame.
- **Session verb internals are engine-thread-only.** The manager's
  underscore verbs (`_create`, `_destroy`, `_attach`, `_detach`,
  `_checkpoint`, `_fetch_board`, `_park`, `_rehydrate`) run under the
  manager lock on the engine thread via `_exec`; calling one from
  outside `gol_tpu/sessions/` bypasses that routing and races the
  engine.
- **Liveness loops never take the manager lock.** A `_heartbeat_loop`
  judging peer freshness must read the lock-free peek surface
  (`peek_turn` / `known` / `peek_geometry`); a manager verb there
  stalls eviction behind a bucket compile — the starvation PR 7 fixed.
- **The serving tier never blocks on device work.** `block_until_ready`
  belongs to the engine/sessions dispatch plane; a server, relay, or
  replay scope that syncs on a device value has smuggled a dispatch
  into the I/O plane.

Per-module and purely name/scope-based (no call graph): the table is a
declaration about WHERE operations may appear, which is exactly what a
scope check can read.
"""

from __future__ import annotations

import ast
from typing import Iterator

from gol_tpu.analysis.core import Finding, ModuleContext
from gol_tpu.analysis.concurrency.graph import tail

CHECK = "thread-ownership"

SCOPE_PREFIX = ("gol_tpu/distributed/", "gol_tpu/relay/",
                "gol_tpu/sessions/", "gol_tpu/replay/")

#: Rule 1 — sanctioned outbound-frame scopes: (path suffix, scope
#: prefix or None for the whole module). The writer plane.
SEND_SANCTIONED = (
    ("distributed/wire.py", None),
    ("relay/ws.py", None),           # WS framing primitives + handshake
    ("distributed/server.py", "_Conn."),
    ("relay/node.py", "WSConn."),
    ("relay/node.py", "RelayNode._reject"),
)
_SEND_TAILS = {"sendall", "send_frame"}

#: Rule 2 — manager verb internals (engine-thread-only via _exec).
_VERB_TAILS = {"_create", "_destroy", "_attach", "_detach", "_checkpoint",
               "_fetch_board", "_park", "_rehydrate"}
#: Receiver tails that denote the session manager.
_MANAGER_TAILS = {"manager", "mgr", "_manager"}

#: Rule 3 — manager surface forbidden in liveness loops (the lock-free
#: peeks `peek_turn` / `known` / `peek_geometry` are the sanctioned
#: alternative and are absent from this set).
_LIVENESS_FORBIDDEN = {"get", "attach", "detach", "create", "destroy",
                       "checkpoint", "fetch_board", "park", "resync",
                       "list_sessions", "pump"}
_LIVENESS_SCOPES = ("_heartbeat_loop",)

#: Rule 4 — device-plane ops banned from the I/O tier.
_DEVICE_TAILS = {"block_until_ready"}
_DEVICE_BANNED_PREFIX = ("gol_tpu/distributed/", "gol_tpu/relay/",
                         "gol_tpu/replay/")


def _send_sanctioned(ctx: ModuleContext, node: ast.AST) -> bool:
    scope = ctx.scope_of(node)
    for suffix, prefix in SEND_SANCTIONED:
        if not ctx.rel.endswith(suffix):
            continue
        if prefix is None or scope == prefix.rstrip(".") \
                or scope.startswith(prefix):
            return True
    return False


def run(ctx: ModuleContext) -> Iterator[Finding]:
    if not ctx.rel.startswith(SCOPE_PREFIX):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = tail(fn)
        if name in _SEND_TAILS and not _send_sanctioned(ctx, node):
            yield ctx.finding(
                CHECK, node,
                f"outbound frame ({name}) outside the writer plane's "
                "sanctioned scopes — enqueue through a _Conn/WriterPool "
                "so backpressure accounting and shed policy see it",
            )
        elif name in _VERB_TAILS and isinstance(fn, ast.Attribute) \
                and tail(fn.value) in _MANAGER_TAILS \
                and not ctx.rel.startswith("gol_tpu/sessions/"):
            yield ctx.finding(
                CHECK, node,
                f"manager verb internal .{name}() called outside the "
                "manager — verbs are engine-thread-only; call the "
                f"public {name.lstrip('_')}() so _exec routes it",
            )
        elif name in _LIVENESS_FORBIDDEN and isinstance(fn, ast.Attribute) \
                and tail(fn.value) in _MANAGER_TAILS:
            scope = ctx.scope_of(node)
            if scope.rsplit(".", 1)[-1] in _LIVENESS_SCOPES:
                yield ctx.finding(
                    CHECK, node,
                    f"liveness loop calls manager.{name}() — a verb "
                    "waits out the manager lock (held across bucket "
                    "compiles); judge freshness on the lock-free "
                    "peek_turn/known surface instead",
                )
        elif name in _DEVICE_TAILS \
                and ctx.rel.startswith(_DEVICE_BANNED_PREFIX):
            yield ctx.finding(
                CHECK, node,
                "device sync (block_until_ready) in the serving tier — "
                "device dispatch is engine-thread-only; consume the "
                "engine's event stream instead of syncing on arrays",
            )
