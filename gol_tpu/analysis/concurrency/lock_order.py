"""lock-order — cycles in the project's merged lock-acquisition graph.

The static face of the AB/BA deadlock: thread 1 takes `_conn_lock`
then (through `manager.detach`) the manager lock, while the engine
thread holds the manager lock and (through an `on_close` sink) takes
`_conn_lock` — the exact PR 12 shape, shipped and hand-debugged. Every
`with B:` while A is lexically held adds edge A→B; calls made while
holding A add A→L for every lock L the resolved callee may acquire
(transitively). A cycle in the merged digraph means two threads can
interleave those paths into a deadlock.

Self-edges are ignored: re-acquiring the same identity is the RLock
re-entrancy pattern (`SessionManager._lock` is an RLock for exactly
this), not an ordering hazard. Each edge of a cycle yields its own
finding at its witness site — the actionable fix is breaking ONE edge
(usually by moving a call outside the lock, as PR 12 did), and the
allowlist key must point at code someone can edit.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Sequence, Tuple

from gol_tpu.analysis.core import Finding, ModuleContext
from gol_tpu.analysis.concurrency.graph import ProjectIndex, index_for

CHECK = "lock-order"

#: Paths whose witnesses may yield findings — the threaded serving
#: plane. The index still covers the whole tree (a cycle may pass
#: through any module); only the flagged EDGE must sit in scope.
SCOPE_PREFIX = ("gol_tpu/distributed/", "gol_tpu/relay/",
                "gol_tpu/sessions/", "gol_tpu/replay/", "gol_tpu/engine/")


def _edges(index: ProjectIndex) -> Dict[Tuple[str, str], tuple]:
    """(A, B) -> first witness (ctx, node, scope, detail)."""
    out: Dict[Tuple[str, str], tuple] = {}
    for fn in index.funcs:
        for acq in fn.acquires:
            for held in acq.held:
                if held != acq.lock:
                    out.setdefault(
                        (held, acq.lock),
                        (fn.ctx, acq.node, fn.qualname,
                         f"acquires {acq.lock} while holding {held}"))
        for cs in fn.calls:
            if not cs.held or not cs.targets:
                continue
            for target in cs.targets:
                for lock in index.acquired_transitively(target):
                    for held in cs.held:
                        if held != lock:
                            out.setdefault(
                                (held, lock),
                                (fn.ctx, cs.node, fn.qualname,
                                 f"holds {held} across a call to "
                                 f"{target.qualname}, which may acquire "
                                 f"{lock}"))
    return out


def _cyclic_edges(edges: Sequence[Tuple[str, str]]) -> List[tuple]:
    """Edges on some cycle, each with one witness cycle path."""
    adj: Dict[str, List[str]] = {}
    for a, b in edges:
        adj.setdefault(a, []).append(b)

    def path(src: str, dst: str) -> List[str]:
        """A simple path src..dst in adj, or [] (DFS)."""
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, p = stack.pop()
            if node == dst:
                return p
            for nxt in adj.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, p + [nxt]))
        return []

    out = []
    for a, b in edges:
        back = path(b, a)
        if back:
            out.append(((a, b), back))
    return out


def run_project(ctxs: Sequence[ModuleContext]) -> Iterator[Finding]:
    index = index_for(ctxs)
    edges = _edges(index)
    for (a, b), back in _cyclic_edges(list(edges)):
        ctx, node, scope, detail = edges[(a, b)]
        if not ctx.rel.startswith(SCOPE_PREFIX):
            continue
        cycle = " -> ".join([a, b] + back[1:])
        yield ctx.finding(
            CHECK, node,
            f"lock-order cycle {cycle}: this site {detail} — another "
            "thread taking them in the opposite order deadlocks both "
            "(the PR 12 detach shape); move the inner acquisition "
            "outside the outer lock",
        )
