"""lockcheck — the static lock-graph pass's dynamic twin.

Opt-in via `GOL_TPU_LOCKCHECK=1` (the `GOL_TPU_CHECK_INVARIANTS`
idiom: creation-time gating, zero overhead when off — `make_lock`
returns a plain `threading.Lock` and nothing below ever runs). When
on, every serving-plane lock created through `make_lock`/`make_rlock`
is a TrackedLock, and three monitors run:

- **Runtime order graph.** Each thread's held stack feeds a merged
  acquisition-order digraph — the same edges the static lock-order
  pass derives from the AST, but witnessed by real interleavings
  (callback indirection, `on_close` sinks, anything resolution can't
  see). An edge that closes a cycle is a potential deadlock and is
  reported BEFORE the acquisition blocks, so the report lands even
  when (especially when) the interleaving would hang.
- **Held-too-long watchdog.** A daemon sweeper flags any lock held
  past `GOL_TPU_LOCKCHECK_MAX_HELD_SECS` (default 10s — above a cold
  CPU bucket compile, far below a test timeout): either a deadlock in
  progress or a blocking call smuggled under a lock that the static
  pass's call graph couldn't resolve.
- **Resource census.** `resource_census()` snapshots what teardown
  must not leak: non-daemon threads, listening server sockets (via
  /proc on Linux), and labeled per-entity metric series still in the
  obs registry. `gol_tpu.testing.leaks` turns the before/after delta
  into per-test assertions.

Every report increments `gol_tpu_lockcheck_violations_total{kind=...}`
(the PR 1 violation-counter discipline — bench_compare gates it
off-zero as an infinite regression), lands a PR 5 flight note, and is
kept in a bounded in-process list for test assertions
(`reports()` / `reports_total()`).

Like `invariants`, this module imports neither jax nor the engine —
gol_tpu.obs is pure stdlib — so the serving modules can import it
unconditionally.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Set, Tuple

from gol_tpu import obs

__all__ = [
    "enable",
    "lockcheck_enabled",
    "make_lock",
    "make_rlock",
    "reports",
    "reports_total",
    "resource_census",
]

_VIOLATIONS = {
    kind: obs.counter(
        "gol_tpu_lockcheck_violations_total",
        "Runtime lock-order cycles and held-too-long watchdog hits",
        {"kind": kind},
    ) for kind in ("lock-order", "held-too-long")
}


def lockcheck_enabled() -> bool:
    return os.environ.get("GOL_TPU_LOCKCHECK", "") == "1"


def enable(on: bool = True) -> None:
    """Programmatic switch; creation-time gating means it must be set
    BEFORE the servers under test build their locks (the env var form
    is what multi-process jobs inherit)."""
    if on:
        os.environ["GOL_TPU_LOCKCHECK"] = "1"
    else:
        os.environ.pop("GOL_TPU_LOCKCHECK", None)


def _max_held_secs() -> float:
    try:
        return float(os.environ.get("GOL_TPU_LOCKCHECK_MAX_HELD_SECS", "10"))
    except ValueError:
        return 10.0


def reports_total() -> int:
    """Total lockcheck reports this process — the number that must stay
    0 across any healthy run (tests assert the per-test delta)."""
    return int(sum(c.value for c in _VIOLATIONS.values()))


def reports() -> List[dict]:
    with _meta:
        return list(_reports)


def make_lock(name: str):
    """A lock for the serving plane: plain `threading.Lock` when
    lockcheck is off (zero overhead — the metrics-off discipline), a
    TrackedLock when on. `name` should be the lock's static identity
    (`_Conn._lock`, `SessionManager._lock`) so runtime reports and
    static findings speak the same language."""
    if not lockcheck_enabled():
        return threading.Lock()
    return _TrackedLock(name, threading.Lock(), reentrant=False)


def make_rlock(name: str):
    if not lockcheck_enabled():
        return threading.RLock()
    return _TrackedLock(name, threading.RLock(), reentrant=True)


# -- tracked state (all guarded by _meta) ---------------------------------

_meta = threading.Lock()
_tls = threading.local()
#: (held, acquired) -> witness description, merged across all threads.
_edges: Dict[Tuple[str, str], str] = {}
#: Cycles already reported, as frozensets of lock names.
_seen_cycles: Set[frozenset] = set()
#: Live holds: (thread_id, name) -> [t0, thread_name, reported_flag].
_holds: Dict[Tuple[int, str], list] = {}
_reports: deque = deque(maxlen=256)
_watchdog_started = False


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def _report(kind: str, msg: str) -> None:
    _VIOLATIONS[kind].inc()
    _reports.append({"kind": kind, "msg": msg, "ts": time.time()})
    from gol_tpu.obs import flight

    flight.note("lockcheck.violation", violation=kind, msg=msg)


def _reaches(frm: str, to: str) -> Optional[List[str]]:
    """A path frm..to in the order graph (holding _meta), or None."""
    stack = [(frm, [frm])]
    seen = {frm}
    while stack:
        node, path = stack.pop()
        if node == to:
            return path
        for (a, b) in _edges:
            if a == node and b not in seen:
                seen.add(b)
                stack.append((b, path + [b]))
    return None


def _note_acquire(name: str) -> None:
    """Record order edges for acquiring `name` with the current
    thread's stack held; report any cycle the new edges close. Called
    BEFORE the raw acquire so a true deadlock still gets its report."""
    held = [e[0] for e in _stack()]
    if not held:
        return
    tname = threading.current_thread().name
    with _meta:
        for h in held:
            if h == name:
                continue
            _edges.setdefault((h, name),
                              f"thread {tname} took {name} holding {h}")
            back = _reaches(name, h)
            if back is not None:
                cyc = frozenset(back + [name])
                if cyc not in _seen_cycles:
                    _seen_cycles.add(cyc)
                    _report(
                        "lock-order",
                        "potential deadlock: acquisition-order cycle "
                        + " -> ".join([h, name] + back[1:])
                        + f" (latest edge: thread {tname} took {name} "
                          f"while holding {h})")


class _TrackedLock:
    """Order-graph + watchdog instrumentation around a raw lock. Only
    the `with` protocol and acquire/release are supported — the only
    surface the serving plane uses."""

    __slots__ = ("name", "_raw", "_reentrant")

    def __init__(self, name: str, raw, reentrant: bool):
        self.name = name
        self._raw = raw
        self._reentrant = reentrant
        _start_watchdog()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        st = _stack()
        for entry in st:
            if entry[0] == self.name and self._reentrant:
                ok = self._raw.acquire(blocking, timeout)
                if ok:
                    entry[2] += 1
                return ok
        _note_acquire(self.name)
        ok = self._raw.acquire(blocking, timeout)
        if ok:
            st.append([self.name, time.monotonic(), 1])
            key = (threading.get_ident(), self.name)
            with _meta:
                _holds[key] = [time.monotonic(),
                               threading.current_thread().name, False]
        return ok

    def release(self) -> None:
        st = _stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i][0] != self.name:
                continue
            st[i][2] -= 1
            if st[i][2] > 0:
                break
            held_for = time.monotonic() - st[i][1]
            del st[i]
            key = (threading.get_ident(), self.name)
            with _meta:
                hold = _holds.pop(key, None)
            limit = _max_held_secs()
            if held_for > limit and not (hold and hold[2]):
                # The watchdog may have reported this hold already.
                _report(
                    "held-too-long",
                    f"{self.name} held {held_for:.1f}s by thread "
                    f"{threading.current_thread().name} "
                    f"(limit {limit:.1f}s) — blocking work under a "
                    "lock, or a deadlock that resolved late")
            break
        self._raw.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


def _start_watchdog() -> None:
    global _watchdog_started
    with _meta:
        if _watchdog_started:
            return
        _watchdog_started = True
    t = threading.Thread(target=_watchdog_loop, name="gol-lockcheck-watchdog",
                         daemon=True)
    t.start()


def _watchdog_loop() -> None:
    while True:
        limit = _max_held_secs()
        time.sleep(min(1.0, limit / 4))
        now = time.monotonic()
        with _meta:
            stuck = [(key, h) for key, h in _holds.items()
                     if not h[2] and now - h[0] > limit]
            for _, h in stuck:
                h[2] = True
        for (tid, name), h in stuck:
            _report(
                "held-too-long",
                f"{name} STILL held after {now - h[0]:.1f}s by thread "
                f"{h[1]} (limit {limit:.1f}s) — likely deadlocked or "
                "blocking under the lock")


# -- teardown resource census ---------------------------------------------

#: Label keys that mark a metric series per-entity — the ones whose
#: teardown must registry.remove() them (bounded-cardinality rule).
_ENTITY_LABEL_KEYS = ("session", "sid", "peer", "conn")


def resource_census() -> dict:
    """What a clean teardown leaves behind: nothing. Keys:

    - `non_daemon_threads`: live non-daemon threads other than main —
      each would hang interpreter exit;
    - `listen_sockets`: this process's LISTENing TCP sockets
      ("host:port"; [] on platforms without /proc) — an unclosed
      server listener;
    - `entity_series`: labeled per-entity metric series (session/peer
      keys) still registered — a destroyed entity that skipped
      `registry.remove` (unbounded growth under churn).

    Callers diff two snapshots around a test (gol_tpu.testing.leaks);
    absolute contents are meaningful only for a fresh process."""
    threads = sorted(
        t.name for t in threading.enumerate()
        if t.is_alive() and not t.daemon and t is not threading.main_thread()
    )
    series = sorted(
        f"{m.name}{{{','.join(f'{k}={v}' for k, v in m.labels)}}}"
        for m in obs.registry().metrics()
        if any(k in _ENTITY_LABEL_KEYS for k, _ in (m.labels or ()))
    )
    return {
        "non_daemon_threads": threads,
        "listen_sockets": _listen_sockets(),
        "entity_series": series,
    }


def _listen_sockets() -> List[str]:
    """local addresses of LISTENing TCP sockets owned by this process,
    via /proc (Linux; [] elsewhere — the census degrades, the thread
    half still works)."""
    try:
        inodes = set()
        fd_dir = f"/proc/{os.getpid()}/fd"
        for fd in os.listdir(fd_dir):
            try:
                target = os.readlink(os.path.join(fd_dir, fd))
            except OSError:
                continue
            if target.startswith("socket:["):
                inodes.add(target[8:-1])
        out = []
        for table in ("/proc/net/tcp", "/proc/net/tcp6"):
            try:
                with open(table) as f:
                    lines = f.readlines()[1:]
            except OSError:
                continue
            for line in lines:
                parts = line.split()
                if len(parts) < 10 or parts[3] != "0A":  # 0A = LISTEN
                    continue
                if parts[9] not in inodes:
                    continue
                addr, port = parts[1].rsplit(":", 1)
                out.append(f"{_hex_addr(addr)}:{int(port, 16)}")
        return sorted(out)
    except OSError:
        return []


def _hex_addr(h: str) -> str:
    if len(h) == 8:  # IPv4, little-endian hex
        b = bytes.fromhex(h)
        return ".".join(str(x) for x in b[::-1])
    return f"[{h}]"  # IPv6: opaque but stable for diffing
