"""Project index for the concurrency checks — locks, calls, held sets.

Pure `ast` + stdlib (the linter's ground rule: it must run where the
package under analysis cannot import). The index is deliberately
name-based where dataflow would be needed for precision, with the same
philosophy as blocking_io's tail matching: the point is that a module
*documents* its locking discipline in names and structure, and the
checks read that documentation.

What gets resolved, and how:

- **Lock identity.** A `with`-item is a lock acquisition when its
  context expression is a bare Name/Attribute that either resolves to
  a known lock binding (`self.X = threading.Lock()` / `RLock` /
  `lockcheck.make_lock(...)`, or a module-level such assignment) or
  whose tail name looks like a lock (`...lock`, `...gate`, `...mutex`).
  `self.X` in class C identifies as `C.X` — walking single-inheritance
  bases to the class that actually BINDS the attr, so `WSConn` methods
  acquiring the `_Conn`-bound `self._lock` merge with `_Conn`'s own
  acquisitions into one graph node. Unresolvable attribute chains get
  a scope-unique identity: they can still witness "held across a
  blocking call" but never merge with someone else's lock (no false
  cycle from two unrelated `.lock` fields).
- **Call targets.** `self.m()` → own class then bases; `self.attr.m()`
  via the attr's constructor type (`self.attr = ClassName(...)` or an
  `attr: ClassName` annotation); `local.m()` via a same-function
  `local = ClassName(...)` assignment; `mod.f()` via the import map
  when `mod` is a project module; bare `f()` via the module's own
  top-level functions. Anything else stays unresolved — the checks
  treat unresolved calls as non-blocking/non-acquiring (conservative:
  silence over noise).
- **Held sets.** A statement-level walk per function tracks the tuple
  of lock identities lexically held at every node, in acquisition
  order.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from gol_tpu.analysis.core import ModuleContext

__all__ = ["ProjectIndex", "FuncInfo", "ClassInfo", "CallSite",
           "BlockingOp", "Acquire", "blocking_op", "index_for", "tail"]

#: Callables that bind a lock: stdlib constructors plus the dynamic
#: twin's tracked factory (lockcheck.make_lock / make_rlock).
_LOCK_FACTORY_TAILS = {"Lock", "RLock", "make_lock", "make_rlock"}
#: Name-pattern fallback for with-items with no resolvable binding.
_LOCK_NAME_RE = re.compile(r"(lock|gate|mutex)s?$", re.I)

#: Chain tails that block the calling thread. `wait`/`join`/queue ops
#: are bounded by deadlines in this codebase but still block for up to
#: the deadline — exactly what must never happen under a lock.
_BLOCKING_TAILS = {
    "sendall": "socket sendall",
    "send_frame": "wire send_frame",
    "send_msg": "wire send_msg",
    "recv_msg": "wire recv_msg",
    "recv_frame": "wire recv_frame",
    "recv": "socket recv",
    "recv_into": "socket recv_into",
    "accept": "socket accept",
    "connect": "socket connect",
    "create_connection": "socket connect",
    "block_until_ready": "device sync (block_until_ready)",
    "sleep": "time.sleep",
    "select": "select",
    "wait": "event/condition wait",
    "join": "thread join",
}
#: `.join` receivers that are string/path joins, not thread joins.
_JOIN_EXEMPT_BASES = {"path", "os", "posixpath", "sep"}


def tail(node: ast.AST) -> Optional[str]:
    """Final attribute/name of a dotted chain (blocking_io's helper)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def blocking_op(node: ast.Call) -> Optional[str]:
    """Description when `node` is a call that blocks its thread."""
    t = tail(node.func)
    desc = _BLOCKING_TAILS.get(t or "")
    if desc is None:
        # Deadlined queue ops: .get/.put WITH a timeout kwarg — the
        # spelling this codebase uses for bounded queue waits (a bare
        # dict .get never carries one).
        if t in ("get", "put") and any(kw.arg == "timeout"
                                       for kw in node.keywords):
            return f"deadlined queue .{t}"
        return None
    if t == "join":
        if not isinstance(node.func, ast.Attribute):
            return None
        base = node.func.value
        if isinstance(base, ast.Constant) or isinstance(base, ast.JoinedStr):
            return None  # "sep".join(...)
        if tail(base) in _JOIN_EXEMPT_BASES:
            return None  # os.path.join(...)
    if t in ("recv", "recv_into", "accept", "connect", "wait") \
            and not isinstance(node.func, ast.Attribute):
        return None  # bare names of these are not socket/event methods
    return desc


def _is_lock_factory(value: ast.AST) -> bool:
    return isinstance(value, ast.Call) and \
        tail(value.func) in _LOCK_FACTORY_TAILS


@dataclasses.dataclass
class Acquire:
    """One `with <lock>:` acquisition."""

    lock: str                  #: lock identity
    node: ast.AST              #: the With statement
    held: Tuple[str, ...]      #: identities already held at this point


@dataclasses.dataclass
class BlockingOp:
    desc: str
    node: ast.AST
    held: Tuple[str, ...]


@dataclasses.dataclass
class CallSite:
    node: ast.Call
    held: Tuple[str, ...]
    targets: List["FuncInfo"]  #: resolved project-internal callees


@dataclasses.dataclass
class FuncInfo:
    """One analyzed function/method."""

    ctx: ModuleContext
    node: ast.AST
    qualname: str
    cls: Optional["ClassInfo"]
    acquires: List[Acquire] = dataclasses.field(default_factory=list)
    blocking: List[BlockingOp] = dataclasses.field(default_factory=list)
    calls: List[CallSite] = dataclasses.field(default_factory=list)

    @property
    def rel(self) -> str:
        return self.ctx.rel


@dataclasses.dataclass
class ClassInfo:
    name: str
    qualname: str
    ctx: ModuleContext
    node: ast.ClassDef
    bases: List[str] = dataclasses.field(default_factory=list)
    methods: Dict[str, FuncInfo] = dataclasses.field(default_factory=dict)
    #: self.X = ClassName(...) / self.X: ClassName — light type facts.
    attr_types: Dict[str, str] = dataclasses.field(default_factory=dict)
    #: Attrs bound to a Lock/RLock/make_lock in any method.
    lock_attrs: Set[str] = dataclasses.field(default_factory=set)


def _dotted(rel: str) -> str:
    """'gol_tpu/relay/node.py' -> 'gol_tpu.relay.node'."""
    return rel[:-3].replace("/", ".") if rel.endswith(".py") else rel


class ProjectIndex:
    """Everything the concurrency checks share, built once per lint."""

    def __init__(self, ctxs: Sequence[ModuleContext]):
        self.ctxs = list(ctxs)
        self.modules: Dict[str, ModuleContext] = {
            _dotted(c.rel): c for c in self.ctxs
        }
        #: class simple name -> every ClassInfo carrying it.
        self.classes: Dict[str, List[ClassInfo]] = {}
        #: per module: top-level function name -> FuncInfo.
        self.mod_funcs: Dict[ModuleContext, Dict[str, FuncInfo]] = {}
        #: per module: imported name -> dotted module or class name.
        self.imports: Dict[ModuleContext, Dict[str, str]] = {}
        #: per module: module-level lock names.
        self.mod_locks: Dict[ModuleContext, Set[str]] = {}
        self.funcs: List[FuncInfo] = []
        self._trans_blocking: Optional[Dict[int, str]] = None
        self._trans_acquires: Optional[Dict[int, Set[str]]] = None
        for ctx in self.ctxs:
            self._register_module(ctx)
        for fn in self.funcs:
            self._analyze(fn)

    # -- pass 1: declarations ---------------------------------------------

    def _register_module(self, ctx: ModuleContext) -> None:
        funcs: Dict[str, FuncInfo] = {}
        imports: Dict[str, str] = {}
        locks: Set[str] = set()
        for node in ctx.tree.body:
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                self._record_import(node, imports)
            elif isinstance(node, ast.FunctionDef):
                fi = FuncInfo(ctx, node, ctx.qualname(node), None)
                funcs[node.name] = fi
                self.funcs.append(fi)
            elif isinstance(node, ast.ClassDef):
                self._register_class(ctx, node)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and _is_lock_factory(node.value):
                locks.add(node.targets[0].id)
        self.mod_funcs[ctx] = funcs
        self.imports[ctx] = imports
        self.mod_locks[ctx] = locks

    def _record_import(self, node: ast.AST, out: Dict[str, str]) -> None:
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                out[a.asname or a.name] = f"{node.module}.{a.name}"

    def _register_class(self, ctx: ModuleContext,
                        node: ast.ClassDef) -> None:
        ci = ClassInfo(node.name, ctx.qualname(node), ctx, node,
                       bases=[tail(b) or "" for b in node.bases])
        for item in node.body:
            if isinstance(item, ast.FunctionDef):
                fi = FuncInfo(ctx, item, ctx.qualname(item), ci)
                ci.methods[item.name] = fi
                self.funcs.append(fi)
        # Attribute facts from every method body: `self.X = Y(...)`
        # types the attr, `self.X = Lock()` marks it a lock binding;
        # `self.X: T` annotations count as types too.
        for sub in ast.walk(node):
            target = value = None
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                target, value = sub.targets[0], sub.value
            elif isinstance(sub, ast.AnnAssign):
                target, value = sub.target, sub.value
            if not (isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"):
                continue
            if value is not None and _is_lock_factory(value):
                ci.lock_attrs.add(target.attr)
            elif isinstance(value, ast.Call):
                t = tail(value.func)
                if t and t[:1].isupper():
                    ci.attr_types.setdefault(target.attr, t)
            if isinstance(sub, ast.AnnAssign):
                ann = tail(sub.annotation)
                if ann and ann[:1].isupper():
                    ci.attr_types.setdefault(target.attr, ann)
        self.classes.setdefault(node.name, []).append(ci)

    # -- name/type resolution ---------------------------------------------

    def resolve_class(self, ctx: ModuleContext,
                      name: str) -> Optional[ClassInfo]:
        """A class by simple name as seen from `ctx`: same module first,
        then the import map, then a project-unique name."""
        cands = self.classes.get(name, [])
        for ci in cands:
            if ci.ctx is ctx:
                return ci
        imp = self.imports.get(ctx, {}).get(name)
        if imp:
            mod = imp.rsplit(".", 1)[0]
            for ci in cands:
                if _dotted(ci.ctx.rel) == mod:
                    return ci
        if len(cands) == 1:
            return cands[0]
        return None

    def _mro(self, ci: ClassInfo) -> Iterator[ClassInfo]:
        seen = set()
        stack = [ci]
        while stack:
            cur = stack.pop(0)
            if id(cur) in seen:
                continue
            seen.add(id(cur))
            yield cur
            for b in cur.bases:
                base = self.resolve_class(cur.ctx, b) if b else None
                if base is not None:
                    stack.append(base)

    def method(self, ci: ClassInfo, name: str) -> Optional[FuncInfo]:
        for cls in self._mro(ci):
            if name in cls.methods:
                return cls.methods[name]
        return None

    def lock_owner(self, ci: ClassInfo, attr: str) -> ClassInfo:
        """The MRO class that binds `attr` as a lock — so `WSConn`'s
        inherited `self._lock` and `_Conn`'s own are one identity."""
        for cls in self._mro(ci):
            if attr in cls.lock_attrs:
                return cls
        return ci

    # -- pass 2: per-function body analysis --------------------------------

    def _analyze(self, fn: FuncInfo) -> None:
        local_types = self._local_types(fn)
        self._walk_body(fn, fn.node.body, (), local_types)

    def _local_types(self, fn: FuncInfo) -> Dict[str, str]:
        """`v = ClassName(...)` assignments in this function."""
        out: Dict[str, str] = {}
        for sub in ast.walk(fn.node):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                    and isinstance(sub.targets[0], ast.Name) \
                    and isinstance(sub.value, ast.Call):
                t = tail(sub.value.func)
                if t and t[:1].isupper():
                    out.setdefault(sub.targets[0].id, t)
        return out

    def lock_identity(self, fn: FuncInfo, expr: ast.AST,
                      local_types: Optional[Dict[str, str]] = None
                      ) -> Optional[str]:
        """Identity of `expr` as a lock, or None if it isn't one."""
        ctx = fn.ctx
        if isinstance(expr, ast.Name):
            if expr.id in self.mod_locks.get(ctx, ()):
                return f"{_dotted(ctx.rel)}:{expr.id}"
            if _LOCK_NAME_RE.search(expr.id):
                return f"{_dotted(ctx.rel)}:{expr.id}"
            return None
        if not isinstance(expr, ast.Attribute):
            return None
        base, attr = expr.value, expr.attr
        if isinstance(base, ast.Name) and base.id == "self" \
                and fn.cls is not None:
            if attr in _all_lock_attrs(self, fn.cls) \
                    or _LOCK_NAME_RE.search(attr):
                return f"{self.lock_owner(fn.cls, attr).name}.{attr}"
            return None
        # `rec.lock` via a typed local / typed self-attr.
        owner = self._expr_class(fn, base, local_types or {})
        if owner is not None and (attr in _all_lock_attrs(self, owner)
                                  or _LOCK_NAME_RE.search(attr)):
            return f"{self.lock_owner(owner, attr).name}.{attr}"
        if _LOCK_NAME_RE.search(attr):
            # A lock by name with no resolvable owner: scope-unique
            # identity — witnesses held-across-blocking, never merges.
            return f"{_dotted(ctx.rel)}:{fn.qualname}:{attr}"
        return None

    def _expr_class(self, fn: FuncInfo, expr: ast.AST,
                    local_types: Dict[str, str]) -> Optional[ClassInfo]:
        """Light type inference for a call/lock receiver."""
        if isinstance(expr, ast.Name):
            if expr.id == "self":
                return fn.cls
            t = local_types.get(expr.id)
            return self.resolve_class(fn.ctx, t) if t else None
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self" and fn.cls is not None:
            for cls in self._mro(fn.cls):
                t = cls.attr_types.get(expr.attr)
                if t:
                    return self.resolve_class(cls.ctx, t)
        return None

    def _resolve_call(self, fn: FuncInfo, call: ast.Call,
                      local_types: Dict[str, str]) -> List[FuncInfo]:
        f = call.func
        if isinstance(f, ast.Name):
            target = self.mod_funcs.get(fn.ctx, {}).get(f.id)
            if target is not None:
                return [target]
            imp = self.imports.get(fn.ctx, {}).get(f.id)
            if imp and "." in imp:
                mod, name = imp.rsplit(".", 1)
                mctx = self.modules.get(mod)
                if mctx is not None:
                    t = self.mod_funcs.get(mctx, {}).get(name)
                    if t is not None:
                        return [t]
            return []
        if isinstance(f, ast.Attribute):
            # Module-qualified: wire.send_msg(...).
            if isinstance(f.value, ast.Name):
                imp = self.imports.get(fn.ctx, {}).get(f.value.id)
                mctx = self.modules.get(imp) if imp else None
                if mctx is not None:
                    t = self.mod_funcs.get(mctx, {}).get(f.attr)
                    return [t] if t is not None else []
            owner = self._expr_class(fn, f.value, local_types)
            if owner is not None:
                t = self.method(owner, f.attr)
                return [t] if t is not None else []
        return []

    def _with_locks(self, fn: FuncInfo, stmt: ast.With,
                    local_types: Dict[str, str]) -> List[str]:
        out = []
        for item in stmt.items:
            expr = item.context_expr
            if isinstance(expr, ast.Call):
                continue  # contextlib.suppress(...), open(...), ...
            lock = self.lock_identity(fn, expr, local_types)
            if lock is not None:
                out.append(lock)
        return out

    def _walk_body(self, fn: FuncInfo, body, held: Tuple[str, ...],
                   local_types: Dict[str, str]) -> None:
        for stmt in body:
            self._walk_stmt(fn, stmt, held, local_types)

    def _walk_stmt(self, fn: FuncInfo, stmt: ast.AST,
                   held: Tuple[str, ...],
                   local_types: Dict[str, str]) -> None:
        if isinstance(stmt, ast.With):
            locks = self._with_locks(fn, stmt, local_types)
            inner = held
            for lock in locks:
                fn.acquires.append(Acquire(lock, stmt, inner))
                if lock not in inner:
                    inner = inner + (lock,)
            for item in stmt.items:
                self._scan_exprs(fn, item.context_expr, held, local_types)
            self._walk_body(fn, stmt.body, inner, local_types)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # A nested def's body runs when CALLED, not here: analyze
            # it with an empty held set under the same FuncInfo (its
            # findings still anchor to the enclosing scope's context).
            self._walk_body(fn, stmt.body, (), local_types)
            return
        if isinstance(stmt, ast.ClassDef):
            return
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.stmt):
                self._walk_stmt(fn, child, held, local_types)
            elif isinstance(child, ast.excepthandler):
                for inner in child.body:
                    self._walk_stmt(fn, inner, held, local_types)
            elif isinstance(child, ast.expr):
                # Expressions directly in this statement; nested
                # lambdas/comprehensions scan with the SAME held set —
                # a lexical approximation (closure bodies handed to
                # `_exec` run elsewhere), which is what feeds the
                # transitive-blocking closure its verb-body facts.
                self._scan_exprs(fn, child, held, local_types)

    def _scan_exprs(self, fn: FuncInfo, expr: ast.AST,
                    held: Tuple[str, ...],
                    local_types: Dict[str, str]) -> None:
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            desc = blocking_op(node)
            if desc is not None:
                fn.blocking.append(BlockingOp(desc, node, held))
            targets = self._resolve_call(fn, node, local_types)
            fn.calls.append(CallSite(node, held, targets))

    # -- interprocedural closures ------------------------------------------

    def blocking_reason(self, fn: FuncInfo) -> Optional[str]:
        """Why `fn` can block its caller, or None. Transitive through
        resolved calls (fixpoint; unresolved calls assumed cheap)."""
        if self._trans_blocking is None:
            self._trans_blocking = self._fix_blocking()
        return self._trans_blocking.get(id(fn.node))

    def _fix_blocking(self) -> Dict[int, str]:
        reason: Dict[int, str] = {}
        for fn in self.funcs:
            if fn.blocking:
                reason[id(fn.node)] = fn.blocking[0].desc
        changed = True
        while changed:
            changed = False
            for fn in self.funcs:
                if id(fn.node) in reason:
                    continue
                for cs in fn.calls:
                    hit = next((t for t in cs.targets
                                if id(t.node) in reason), None)
                    if hit is not None:
                        reason[id(fn.node)] = (
                            f"calls {hit.qualname} which blocks "
                            f"({reason[id(hit.node)]})")
                        changed = True
                        break
        return reason

    def acquired_transitively(self, fn: FuncInfo) -> Set[str]:
        """Lock identities `fn` may acquire, through resolved calls."""
        if self._trans_acquires is None:
            self._trans_acquires = self._fix_acquires()
        return self._trans_acquires.get(id(fn.node), set())

    def _fix_acquires(self) -> Dict[int, Set[str]]:
        acq: Dict[int, Set[str]] = {
            id(fn.node): {a.lock for a in fn.acquires} for fn in self.funcs
        }
        changed = True
        while changed:
            changed = False
            for fn in self.funcs:
                mine = acq[id(fn.node)]
                for cs in fn.calls:
                    for t in cs.targets:
                        extra = acq.get(id(t.node), set()) - mine
                        if extra:
                            mine |= extra
                            changed = True
        return acq


def _all_lock_attrs(index: ProjectIndex, ci: ClassInfo) -> Set[str]:
    out: Set[str] = set()
    for cls in index._mro(ci):
        out |= cls.lock_attrs
    return out


#: One-slot cache: lint_paths hands every run_project the SAME ctx
#: list, so lock-order and lock-blocking share one index build.
_LAST: List = [None, None]


def index_for(ctxs: Sequence[ModuleContext]) -> ProjectIndex:
    if _LAST[0] is not ctxs:
        _LAST[0] = ctxs
        _LAST[1] = ProjectIndex(ctxs)
    return _LAST[1]
