"""guarded-field — a field locked in one method, mutated bare in another.

The peek-then-pop shape: `PoolHandle.enqueue` mutates `self._q` under
`self._lock`, so the class has declared that deque lock-guarded — a
`self._q.popleft()` in another method with no lock held races every
guarded site (the writer-pool bug `_sending` was invented to fix), and
the double-decremented WS gauge was the AugAssign twin (`self.ws_peers
-= 1` on two threads, one of them bare).

Per class: collect every *mutation* of a `self.X` field — AugAssign,
container mutators (`append`/`pop`/`popleft`/`appendleft`/`remove`/
`clear`/`add`/`discard`/`update`/`extend`/`insert`/`setdefault`), and
subscript stores/deletes — with the set of `with`-lock tails lexically
held. A field mutated at least once under a lock makes every bare
mutation of it a finding. Plain rebinds (`self.turn = t`) are NOT
tracked: rebinding a reference is atomic under the GIL and flagging it
would bury the real races in noise.

Exempt scopes: `__init__` (no concurrent observer exists yet) and the
codebase's `*_locked` naming convention (`_release_locked`,
`_sync_conn_locked` — the caller holds the lock by contract; the
convention IS the documentation this check reads).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Set, Tuple

from gol_tpu.analysis.core import Finding, ModuleContext

CHECK = "guarded-field"

SCOPE_PREFIX = ("gol_tpu/distributed/", "gol_tpu/relay/",
                "gol_tpu/sessions/", "gol_tpu/replay/", "gol_tpu/engine/")

_LOCK_NAME_RE = re.compile(r"(lock|gate|mutex)s?$", re.I)
_MUTATORS = {"append", "appendleft", "pop", "popleft", "remove", "clear",
             "add", "discard", "update", "extend", "insert", "setdefault"}


def _tail(node: ast.AST):
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _self_field(node: ast.AST):
    """'X' when node is `self.X`, else None."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _mutations(stmt: ast.AST) -> Iterator[Tuple[str, ast.AST, str]]:
    """(field, node, kind) for self-field mutations directly in stmt:
    assignment targets first, then container-mutator calls anywhere in
    the statement's direct expressions (`self._q.popleft()` bare or as
    an assignment's right-hand side alike)."""
    if isinstance(stmt, ast.AugAssign):
        f = _self_field(stmt.target)
        if f:
            yield f, stmt, "augmented assignment"
        elif isinstance(stmt.target, ast.Subscript):
            f = _self_field(stmt.target.value)
            if f:
                yield f, stmt, "item update"
    elif isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            if isinstance(t, ast.Subscript):
                f = _self_field(t.value)
                if f:
                    yield f, stmt, "item store"
    elif isinstance(stmt, ast.Delete):
        for t in stmt.targets:
            if isinstance(t, ast.Subscript):
                f = _self_field(t.value)
                if f:
                    yield f, stmt, "item delete"
    for child in ast.iter_child_nodes(stmt):
        if not isinstance(child, ast.expr):
            continue
        for node in ast.walk(child):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _MUTATORS:
                f = _self_field(node.func.value)
                if f:
                    yield f, node, f".{node.func.attr}()"


class _ClassScan:
    def __init__(self) -> None:
        #: field -> lock tails it was mutated under (somewhere).
        self.locked_under: Dict[str, Set[str]] = {}
        #: bare mutation sites: (field, node, kind).
        self.bare: List[Tuple[str, ast.AST, str]] = []

    def walk(self, body, held: Tuple[str, ...]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # nested defs have their own discipline
            inner = held
            if isinstance(stmt, ast.With):
                for item in stmt.items:
                    t = _tail(item.context_expr)
                    if not isinstance(item.context_expr, ast.Call) \
                            and t and _LOCK_NAME_RE.search(t):
                        inner = inner + (t,)
                self.walk(stmt.body, inner)
                continue
            for field, node, kind in _mutations(stmt):
                if held:
                    self.locked_under.setdefault(field, set()).update(held)
                else:
                    self.bare.append((field, node, kind))
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.stmt):
                    self.walk([child], held)
                elif isinstance(child, ast.excepthandler):
                    self.walk(child.body, held)


def run(ctx: ModuleContext) -> Iterator[Finding]:
    if not ctx.rel.startswith(SCOPE_PREFIX):
        return
    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        scan = _ClassScan()
        exempt_sites: Set[int] = set()
        for method in cls.body:
            if not isinstance(method, ast.FunctionDef):
                continue
            before = len(scan.bare)
            scan.walk(method.body, ())
            if method.name == "__init__" or method.name.endswith("_locked"):
                exempt_sites.update(
                    id(node) for _, node, _ in scan.bare[before:])
        for field, node, kind in scan.bare:
            if id(node) in exempt_sites:
                continue
            locks = scan.locked_under.get(field)
            if not locks:
                continue
            yield ctx.finding(
                CHECK, node,
                f"self.{field} {kind} with no lock held, but this class "
                f"mutates it under {', '.join(sorted(locks))} elsewhere "
                "— the peek-then-pop race shape; take the lock here or "
                "rename the method *_locked if the caller holds it",
            )
