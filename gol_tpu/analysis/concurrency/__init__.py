"""Concurrency analysis plane — static lock/thread lint + dynamic twin.

The reference coursework leaned on `go test -race`; this repo re-grew
that channel-and-goroutine architecture as Python threads, where every
race we shipped (the PR 12 detach deadlock, the PR 7 attach-before-
reader eviction, the writer-pool peek-then-pop, the double-decremented
WS gauge) was caught by hand review. This package is the tooling that
review was standing in for:

- `graph.py` — the shared project index: classes, methods, lock
  identities, an interprocedural call graph, and per-statement
  held-lock sets. Pure `ast` + stdlib like the rest of the linter.
- `lock_order.py` — [lock-order] cycles in the merged lock-acquisition
  digraph (a static AB/BA deadlock detector).
- `lock_blocking.py` — [lock-blocking] locks held across blocking
  operations (socket sends/recvs, `manager.attach`/bucket compiles,
  thread joins, deadlined queue ops, `block_until_ready`), directly or
  through the call graph.
- `ownership.py` — [thread-ownership] the declared thread-ownership
  table: outbound frames leave only through writer-plane scopes,
  session verb internals are engine-thread-only, heartbeat/liveness
  loops never take the manager lock, the serving tier never blocks on
  device work.
- `guarded_field.py` — [guarded-field] fields mutated under a class's
  lock in one method and bare in another (the peek-then-pop shape).
- `lockcheck.py` — the dynamic twin (`GOL_TPU_LOCKCHECK=1`): tracked
  locks merging runtime acquisition orders into the same kind of order
  graph, a held-too-long watchdog, and a teardown resource census.

The static checks register in `gol_tpu.analysis.checks.ALL_CHECKS` and
ride `python -m gol_tpu.analysis --strict` with the shrink-only
allowlist discipline; the regression corpus under
`tests/fixtures/concurrency/` proves they flag the bug classes this
codebase actually shipped (`python -m gol_tpu.analysis.concurrency.corpus`).
"""

from gol_tpu.analysis.concurrency import (  # noqa: F401
    guarded_field,
    lock_blocking,
    lock_order,
    ownership,
)

#: The concurrency checks, in report order (appended to ALL_CHECKS).
CONCURRENCY_CHECKS = [lock_order, lock_blocking, ownership, guarded_field]

__all__ = ["CONCURRENCY_CHECKS", "guarded_field", "lock_blocking",
           "lock_order", "ownership"]
