"""Deterministic fault injection for the controller ⇄ engine link.

The resilience layer (heartbeats, auto-reconnect, crash-restart — see
docs/RESILIENCE.md) is only trustworthy if every failure mode it claims
to survive is *reproducibly exercised*, not hoped for. This module is
that harness: a socket proxy that injects a planned fault at exactly
the Nth send/recv operation of a role's sockets — no randomness in
when a fault fires, so a failing test replays bit-for-bit.

Plans come from the `GOL_TPU_FAULTS` environment variable (picked up by
the server's accept path and the client's dial path) or from
`install()` in-process (tests). Spec grammar, rules joined with ';':

    ROLE:KIND@OP:NTH[:ARG]

    ROLE  "client" (sockets the Controller dials) or
          "server" (sockets the EngineServer accepts)
    KIND  reset    hard-RST the connection and raise (both ops)
          delay    sleep ARG seconds before the op (both ops)
          drop     swallow the payload, report success   (send only)
          dup      transmit the payload twice            (send only)
          partial  transmit half the payload, then RST   (send only)
    OP    "send" or "recv"
    NTH   1-based operation count, per (role, op), across every socket
          wrapped for that role in this process
    ARG   kind-specific float (delay seconds)

Examples:

    GOL_TPU_FAULTS="client:reset@recv:40"
        the client's 40th socket read resets the connection mid-stream
        (the auto-reconnect acceptance scenario)
    GOL_TPU_FAULTS="server:delay@send:3:0.25;client:dup@send:7"
        the server's 3rd write stalls 250 ms and the client's 7th
        write is duplicated on the wire

Operation counts are deterministic because the wire protocol is: one
`sendall` per frame, two `recv` syscall-batches per frame (length
header, then payload). Each rule fires exactly once.
"""

from __future__ import annotations

import os
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

__all__ = [
    "FaultPlan",
    "FaultRule",
    "FaultSpecError",
    "FaultySocket",
    "active_plan",
    "clear",
    "install",
    "wrap",
]

_ROLES = ("client", "server")
_OPS = ("send", "recv")
_KINDS = ("reset", "delay", "drop", "dup", "partial")
_SEND_ONLY = ("drop", "dup", "partial")


class FaultSpecError(ValueError):
    """A GOL_TPU_FAULTS spec that does not parse."""


class FaultRule:
    """One planned fault: fire `kind` at the `nth` `op` of `role`."""

    def __init__(self, role: str, kind: str, op: str, nth: int,
                 arg: float = 0.0):
        if role not in _ROLES:
            raise FaultSpecError(f"unknown role {role!r} (want client|server)")
        if kind not in _KINDS:
            raise FaultSpecError(f"unknown fault kind {kind!r}")
        if op not in _OPS:
            raise FaultSpecError(f"unknown op {op!r} (want send|recv)")
        if kind in _SEND_ONLY and op != "send":
            raise FaultSpecError(f"fault {kind!r} applies to send only")
        if nth < 1:
            raise FaultSpecError(f"nth must be >= 1, got {nth}")
        self.role, self.kind, self.op, self.nth, self.arg = (
            role, kind, op, nth, arg
        )
        self.fired = False

    def __repr__(self):  # pragma: no cover - debugging aid
        return (f"FaultRule({self.role}:{self.kind}@{self.op}:"
                f"{self.nth}:{self.arg})")


class FaultPlan:
    """A set of rules plus the per-(role, op) operation counters they
    fire against. One plan is active per process; counters are shared
    across every socket wrapped under it, which is what makes the Nth
    operation well-defined for a multi-connection run."""

    def __init__(self, rules: List[FaultRule]):
        self.rules = list(rules)
        self._counts: Dict[Tuple[str, str], int] = {}
        self._lock = threading.Lock()

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        rules = []
        for raw in spec.replace(",", ";").split(";"):
            part = raw.strip()
            if not part:
                continue
            try:
                role, rest = part.split(":", 1)
                kind_op, tail = rest.split(":", 1)
                kind, op = kind_op.split("@", 1)
                bits = tail.split(":")
                nth = int(bits[0])
                arg = float(bits[1]) if len(bits) > 1 else 0.0
            except (ValueError, IndexError):
                raise FaultSpecError(
                    f"bad fault rule {part!r} — want ROLE:KIND@OP:NTH[:ARG]"
                ) from None
            rules.append(FaultRule(role.strip(), kind.strip(), op.strip(),
                                   nth, arg))
        if not rules:
            raise FaultSpecError(f"no rules in fault spec {spec!r}")
        return cls(rules)

    def next_fault(self, role: str, op: str) -> Optional[FaultRule]:
        """Count one (role, op) operation; the rule to fire now, if any."""
        with self._lock:
            key = (role, op)
            self._counts[key] = n = self._counts.get(key, 0) + 1
            for rule in self.rules:
                if (not rule.fired and rule.role == role and rule.op == op
                        and rule.nth == n):
                    rule.fired = True
                    return rule
        return None

    def counts(self) -> Dict[Tuple[str, str], int]:
        with self._lock:
            return dict(self._counts)


#: Process-global active plan. `wrap()` consults it (falling back to
#: GOL_TPU_FAULTS) so production call sites stay one-liners.
_ACTIVE: Optional[FaultPlan] = None
_ENV_SPEC: Optional[str] = None  # spec the env-derived plan was built from


def install(plan: FaultPlan) -> FaultPlan:
    """Activate a plan programmatically (tests). Pair with `clear()`.
    Clears the env-spec marker so a later GOL_TPU_FAULTS change can
    never silently replace or deactivate the installed plan — install
    wins until clear(), as documented."""
    global _ACTIVE, _ENV_SPEC
    _ACTIVE = plan
    _ENV_SPEC = None
    return plan


def clear() -> None:
    global _ACTIVE, _ENV_SPEC
    _ACTIVE = None
    _ENV_SPEC = None


def active_plan() -> Optional[FaultPlan]:
    """The installed plan, else one lazily built from GOL_TPU_FAULTS.
    An env-derived plan is rebuilt whenever the variable's value
    changes (each test/subprocess run gets fresh counters); a plan
    `install()`ed programmatically wins over the environment until
    `clear()`."""
    global _ACTIVE, _ENV_SPEC
    if _ACTIVE is not None and _ENV_SPEC is None:
        return _ACTIVE  # programmatic install
    spec = os.environ.get("GOL_TPU_FAULTS")
    if not spec:
        _ACTIVE = _ENV_SPEC = None
        return None
    if spec != _ENV_SPEC:
        _ACTIVE = FaultPlan.parse(spec)
        _ENV_SPEC = spec
    return _ACTIVE


def wrap(role: str, sock: socket.socket) -> socket.socket:
    """The one production entry point: proxy `sock` under the active
    plan's rules for `role`, or return it untouched when no plan is
    active — the happy path pays a None check and nothing else."""
    plan = active_plan()
    if plan is None or not any(r.role == role for r in plan.rules):
        return sock
    return FaultySocket(sock, role, plan)


class FaultySocket:
    """Socket proxy injecting planned faults on send/recv.

    Everything not intercepted (settimeout, setsockopt, shutdown,
    close, getsockname, ...) delegates to the real socket, so the
    proxy drops into any call site that holds a socket."""

    def __init__(self, sock: socket.socket, role: str, plan: FaultPlan):
        self._sock = sock
        self._role = role
        self._plan = plan

    def __getattr__(self, name):
        return getattr(self._sock, name)

    def _hard_reset(self) -> None:
        """Close with SO_LINGER 0 so the peer sees an RST, not FIN —
        the abrupt-death shape (power loss, SIGKILL'd kernel peer)."""
        try:
            self._sock.setsockopt(
                socket.SOL_SOCKET, socket.SO_LINGER,
                struct.pack("ii", 1, 0),
            )
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass

    def sendall(self, data, *args):
        rule = self._plan.next_fault(self._role, "send")
        if rule is not None:
            if rule.kind == "delay":
                time.sleep(rule.arg)
            elif rule.kind == "drop":
                return None  # swallowed: the peer sees a framing hole
            elif rule.kind == "dup":
                self._sock.sendall(data, *args)
            elif rule.kind == "partial":
                half = bytes(data)[: max(1, len(data) // 2)]
                try:
                    self._sock.sendall(half, *args)
                finally:
                    self._hard_reset()
                raise ConnectionResetError(
                    "injected fault: partial write then reset"
                )
            elif rule.kind == "reset":
                self._hard_reset()
                raise ConnectionResetError("injected fault: send reset")
        return self._sock.sendall(data, *args)

    def send(self, data, *args):
        # Routed through sendall accounting so N counts whole-frame
        # writes however the caller spells them.
        self.sendall(data, *args)
        return len(data)

    def recv(self, *args):
        rule = self._plan.next_fault(self._role, "recv")
        if rule is not None:
            if rule.kind == "delay":
                time.sleep(rule.arg)
            elif rule.kind == "reset":
                self._hard_reset()
                raise ConnectionResetError("injected fault: recv reset")
        return self._sock.recv(*args)
