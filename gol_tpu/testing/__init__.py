"""gol_tpu.testing — deterministic fault injection for the wire plane.

Production code imports this lazily and only consults it when
`GOL_TPU_FAULTS` is set (or a plan was installed programmatically), so
the package costs nothing on the happy path. See `faults.py` for the
spec grammar and the FaultySocket wrapper.
"""

from gol_tpu.testing.faults import (
    FaultPlan,
    FaultRule,
    FaultSpecError,
    FaultySocket,
    active_plan,
    clear,
    install,
    wrap,
)

__all__ = [
    "FaultPlan",
    "FaultRule",
    "FaultSpecError",
    "FaultySocket",
    "active_plan",
    "clear",
    "install",
    "wrap",
]
