"""gol_tpu.testing — deterministic fault injection and seeded chaos.

Production code imports this lazily and only consults it when
`GOL_TPU_FAULTS` is set (or a plan was installed programmatically), so
the package costs nothing on the happy path. See `faults.py` for the
spec grammar and the FaultySocket wrapper, and `chaos.py` for the
seeded multi-session chaos harness composed on top of it (imported on
demand — it pulls in numpy/stepper machinery the fault plane does not
need). `leaks.py` adds the per-test concurrency guard: lockcheck
forced ON plus a thread/socket leak census around each distributed
test."""

from gol_tpu.testing.faults import (
    FaultPlan,
    FaultRule,
    FaultSpecError,
    FaultySocket,
    active_plan,
    clear,
    install,
    wrap,
)
from gol_tpu.testing.leaks import assert_no_leaks, lockcheck_guard

__all__ = [
    "assert_no_leaks",
    "lockcheck_guard",
    "FaultPlan",
    "FaultRule",
    "FaultSpecError",
    "FaultySocket",
    "active_plan",
    "clear",
    "install",
    "wrap",
]
