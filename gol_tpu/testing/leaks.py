"""Thread/socket leak census for the distributed test modules.

A serving-plane test that leaks a non-daemon thread hangs interpreter
exit; one that leaks a listening socket poisons every later test that
binds port 0 on a crowded CI box; one that leaks per-entity metric
series grows the registry without bound under churn. None of those
show up in the test's own asserts — they show up three modules later.

`lockcheck_guard` is the per-test discipline the distributed modules
(`test_overload`, `test_resilience`, `test_sessions`) wrap in an
autouse fixture, composing three checks around every test:

- forces `GOL_TPU_LOCKCHECK=1` (the invariants-forced-ON pattern), so
  every serving-plane lock built during the test is a TrackedLock;
- asserts zero new lockcheck reports (runtime lock-order cycles,
  held-too-long watchdog hits) over the test;
- asserts the resource census delta is empty at teardown: no new
  non-daemon thread and no new listening socket survives, with a short
  grace loop for teardown that is still winding down (a joined server
  thread takes a beat to leave `threading.enumerate`).

Entity-series growth is reported in the assertion message but does not
gate — a test may legitimately leave session-scoped series behind when
it shares a process-global registry with its neighbors; the smoke
lanes gate those from a fresh process.
"""

from __future__ import annotations

import time

from gol_tpu.analysis.concurrency import lockcheck

__all__ = ["assert_no_leaks", "lockcheck_guard", "snapshot"]

#: Teardown grace: how long a census delta may take to drain before it
#: is a leak (server shutdown joins its threads, but enumerate() can
#: trail by a scheduler beat).
GRACE_SECS = 3.0


def snapshot() -> dict:
    return lockcheck.resource_census()


def _delta(before: dict, after: dict) -> dict:
    out = {}
    for key in ("non_daemon_threads", "listen_sockets", "entity_series"):
        new = [x for x in after.get(key, []) if x not in before.get(key, [])]
        if new:
            out[key] = new
    return out


def assert_no_leaks(before: dict, *, grace: float = GRACE_SECS,
                    what: str = "test") -> None:
    """Fail if the census grew vs `before` and stays grown past the
    grace window. Threads and listeners gate; entity series inform."""
    deadline = time.monotonic() + grace
    while True:
        d = _delta(before, snapshot())
        gating = {k: v for k, v in d.items()
                  if k in ("non_daemon_threads", "listen_sockets")}
        if not gating:
            return
        if time.monotonic() > deadline:
            raise AssertionError(
                f"resource leak after {what}: {gating} "
                f"(entity series delta: {d.get('entity_series', [])})"
            )
        time.sleep(0.05)


def lockcheck_guard(monkeypatch, *, invariants: bool = True):
    """Generator for an autouse fixture: wrap with

        @pytest.fixture(autouse=True)
        def _concurrency_on(monkeypatch):
            yield from lockcheck_guard(monkeypatch)

    Forces LOCKCHECK (and, by default, the runtime invariants) ON for
    the test, then asserts zero lockcheck reports and an empty leak
    census delta at teardown."""
    monkeypatch.setenv("GOL_TPU_LOCKCHECK", "1")
    if invariants:
        monkeypatch.setenv("GOL_TPU_CHECK_INVARIANTS", "1")
    from gol_tpu.analysis.invariants import violations_total

    inv_before = violations_total() if invariants else 0
    reports_before = lockcheck.reports_total()
    census_before = snapshot()
    yield
    if invariants:
        assert violations_total() - inv_before == 0, (
            "a runtime invariant broke during this test"
        )
    new = lockcheck.reports_total() - reports_before
    if new:
        tail = [r for r in lockcheck.reports()][-new:]
        raise AssertionError(
            f"{new} lockcheck report(s) during this test: "
            + "; ".join(f"[{r['kind']}] {r['msg']}" for r in tail)
        )
    assert_no_leaks(census_before)
