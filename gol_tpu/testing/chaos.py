"""Seeded chaos harness for the multi-session serving plane.

The PR 3 fault harness (`gol_tpu.testing.faults`) proves ONE planned
failure at a time. Production dies messier: a server SIGKILLed in the
middle of a verb storm while an observer's reader is wedged and three
control clients are retrying creates through the restart. This module
is the scenario runner for that shape of chaos (docs/RESILIENCE.md
"Chaos harness"): every source of disorder draws from ONE seed, so a
failing scenario replays bit-for-bit, and the end state is judged
against exact oracles —

- **bit-identity**: every surviving session's board must equal the
  fused single-board stepper run of its creation recipe to the same
  turn (`oracle_board`) — i.e. identical to an unfaulted run;
- **ledger consistency**: the live session set must be exactly
  created-minus-destroyed (retried creates never double-create,
  destroyed sessions never resurrect across `--resume latest`);
- **invariant counters at zero**: the PR 1 runtime checkers must not
  have seen a single violation anywhere in the process mesh.

Building blocks (composable in-process — `tests/test_chaos.py` wires
them against a `SessionServer` thread and emulates the crash; the
subprocess `ChaosRunner` adds the real SIGKILL and is what
`scripts/chaos_smoke.sh` drives):

- `VerbStorm`: a thread issuing a seeded create/checkpoint/destroy
  sequence over its own session-id namespace through the idempotent
  retrying `SessionControl`, keeping the ledger of what must exist
  afterwards;
- `ShadowObserver`: a raw-socket watcher of one session that applies
  flips/syncs to a shadow raster, *stalls its reader* on a seeded
  schedule (driving the server's slow-consumer degradation), verifies
  every BoardSync bit-exactly against the oracle, and re-dials
  through crashes;
- `oracle_board`: the unfaulted reference — the creation recipe
  stepped by the fused single-board stepper (bit-equality of that
  stepper vs the session layer is pinned by `tests/test_sessions.py`,
  so the oracle is cheap even for millions of turns).
"""

from __future__ import annotations

import contextlib
import random
import socket
import threading
import time
from typing import Optional

import numpy as np

__all__ = [
    "ChaosError",
    "ChaosRunner",
    "Recipe",
    "ShadowObserver",
    "VerbStorm",
    "oracle_board",
    "parse_metric",
]


class ChaosError(AssertionError):
    """A chaos scenario ended in a state the contract forbids."""


class Recipe:
    """One session's creation recipe — everything needed to rebuild
    its turn-0 board and judge any later state bit-exactly. Life-like
    two-state rules only (the session layer's own restriction)."""

    def __init__(self, sid: str, width: int = 64, height: int = 64,
                 seed: int = 0, density: float = 0.25,
                 rule: str = "B3/S23"):
        self.sid = sid
        self.width = width
        self.height = height
        self.seed = seed
        self.density = density
        self.rule = rule

    def board0(self) -> np.ndarray:
        from gol_tpu.sessions.manager import seeded_board

        return seeded_board(self.height, self.width, self.seed,
                            self.density)

    def create_kwargs(self) -> dict:
        return {"width": self.width, "height": self.height,
                "rule": self.rule, "seed": self.seed,
                "density": self.density}


def oracle_board(recipe: Recipe, turn: int) -> np.ndarray:
    """The unfaulted run's board at `turn`: the recipe's soup stepped
    by the fused single-board stepper (one device dispatch even for
    millions of turns; bit-equal to the session layer by the pinned
    oracle tests). Returns a {0,255} uint8 (H, W) raster."""
    from gol_tpu.parallel.stepper import make_stepper

    s = make_stepper(threads=1, height=recipe.height,
                     width=recipe.width, rule=recipe.rule)
    w = s.put(recipe.board0())
    if turn:
        w, _ = s.step_n(w, int(turn))
    return np.asarray(s.fetch(w), np.uint8)


def parse_metric(text: str, name: str) -> float:
    """Sum every sample of `name` in a Prometheus-text exposition
    (labeled children sum; absent series is 0.0)."""
    total, seen = 0.0, False
    for line in text.splitlines():
        if not line.startswith(name):
            continue
        rest = line[len(name):]
        if rest[:1] not in ("", " ", "{"):
            continue  # a longer name sharing the prefix
        try:
            total += float(line.rsplit(None, 1)[1])
            seen = True
        except (IndexError, ValueError):
            continue
    return total if seen else 0.0


class VerbStorm(threading.Thread):
    """One seeded storm of idempotent session verbs over a private id
    namespace. Every verb goes through `SessionControl`'s retrying
    path, so the storm survives server crashes mid-verb — the ledger
    it keeps is therefore EXACT: after `run` returns without error,
    `alive` names precisely the sessions that must exist (with their
    recipes) and `destroyed` the ones that must never come back."""

    #: Verb mix per step (seeded choice): mostly creates/destroys —
    #: the lifecycle verbs whose idempotency chaos exists to test.
    _OPS = ("create", "create", "destroy", "checkpoint", "list")

    def __init__(self, address, *, seed: int, prefix: str,
                 verbs: int = 24, board_side: int = 64,
                 secret: Optional[str] = None,
                 retry_window: float = 60.0,
                 on_verb=None):
        super().__init__(name=f"chaos-storm-{prefix}", daemon=True)
        self._address = address
        self._rng = random.Random(seed)
        self._prefix = prefix
        self._verbs = verbs
        self._side = board_side
        self._secret = secret
        self._window = retry_window
        #: Called after every completed verb (the runner's SIGKILL
        #: trigger counts these across storms).
        self._on_verb = on_verb or (lambda: None)
        self.alive: "dict[str, Recipe]" = {}
        self.destroyed: "set[str]" = set()
        self.completed = 0
        self.error: Optional[BaseException] = None

    def _recipe(self, i: int) -> Recipe:
        return Recipe(f"{self._prefix}-{i}", width=self._side,
                      height=self._side,
                      seed=self._rng.randrange(2 ** 31),
                      density=0.2 + 0.2 * self._rng.random())

    def run(self) -> None:
        from gol_tpu.distributed.client import SessionControl

        try:
            ctl = SessionControl(*self._address, secret=self._secret,
                                 timeout=15.0,
                                 retry_window=self._window,
                                 retry_seed=self._rng.randrange(2 ** 31))
        except BaseException as e:
            self.error = e
            return
        try:
            ids = [self._recipe(i) for i in range(4)]
            for _ in range(self._verbs):
                op = self._rng.choice(self._OPS)
                r = ids[self._rng.randrange(len(ids))]
                try:
                    if op == "create" and r.sid not in self.alive:
                        ctl.create(r.sid, **r.create_kwargs())
                        self.alive[r.sid] = r
                        self.destroyed.discard(r.sid)
                    elif op == "destroy" and r.sid in self.alive:
                        ctl.destroy(r.sid)
                        del self.alive[r.sid]
                        self.destroyed.add(r.sid)
                    elif op == "checkpoint" and r.sid in self.alive:
                        ctl.checkpoint(r.sid)
                    else:
                        ctl.list()
                except ValueError as e:
                    # SessionError without a ConnectionError pedigree:
                    # max-sessions past the retry window is legal
                    # under admission chaos; anything else is a bug.
                    if str(e) != "max-sessions":
                        raise
                self.completed += 1
                self._on_verb()
        except BaseException as e:
            self.error = e
        finally:
            with contextlib.suppress(Exception):
                ctl.close()


class ShadowObserver(threading.Thread):
    """Raw-socket watcher of one session: maintains a shadow raster
    from syncs + flips (synced_turn-gated, exactly the client
    contract), STALLS its own reader on a seeded schedule to drive the
    server's slow-consumer degradation, verifies every BoardSync
    bit-exactly against the incremental oracle, and re-dials through
    server crashes. `errors` collects contract violations (a non-empty
    list fails the scenario)."""

    def __init__(self, address, recipe: Recipe, *, seed: int,
                 secret: Optional[str] = None,
                 stall_secs: float = 1.0, stall_every: int = 40,
                 rcvbuf: int = 4096):
        super().__init__(name=f"chaos-observe-{recipe.sid}", daemon=True)
        self._address = address
        self._recipe = recipe
        self._rng = random.Random(seed)
        self._secret = secret
        self._stall_secs = stall_secs
        self._stall_every = max(1, stall_every)
        self._rcvbuf = rcvbuf
        self._halt = threading.Event()
        self.errors: "list[str]" = []
        self.syncs = 0
        self.verified_turn = 0
        self.stalls = 0
        # Incremental oracle: the recipe's board stepped to
        # `self._oracle_turn` by the fused stepper (cheap deltas).
        self._stepper = None
        self._oracle_w = None
        self._oracle_turn = 0
        self._shadow: Optional[np.ndarray] = None
        self._shadow_turn = -1

    def stop(self) -> None:
        self._halt.set()

    # --- oracle ---

    def _oracle_at(self, turn: int) -> np.ndarray:
        from gol_tpu.parallel.stepper import make_stepper

        r = self._recipe
        if self._stepper is None:
            self._stepper = make_stepper(threads=1, height=r.height,
                                         width=r.width, rule=r.rule)
            self._oracle_w = self._stepper.put(r.board0())
            self._oracle_turn = 0
        if turn < self._oracle_turn:  # restart (resumed below a peak)
            self._oracle_w = self._stepper.put(r.board0())
            self._oracle_turn = 0
        if turn > self._oracle_turn:
            self._oracle_w, _ = self._stepper.step_n(
                self._oracle_w, turn - self._oracle_turn
            )
            self._oracle_turn = turn
        return np.asarray(self._stepper.fetch(self._oracle_w), np.uint8)

    def _check(self, turn: int, what: str) -> None:
        want = self._oracle_at(turn) != 0
        if not np.array_equal(self._shadow != 0, want):
            self.errors.append(
                f"{self._recipe.sid}: {what} at turn {turn} diverges "
                f"from the unfaulted oracle"
            )
        else:
            self.verified_turn = max(self.verified_turn, turn)

    # --- the watching loop ---

    def run(self) -> None:
        while not self._halt.is_set():
            try:
                self._watch_once()
            except (OSError, ConnectionError, TimeoutError):
                # Server down (crash window) or our stall got us
                # evicted past the drain deadline: re-dial.
                time.sleep(0.2 + 0.3 * self._rng.random())
            except Exception as e:  # contract bug, not chaos
                self.errors.append(
                    f"{self._recipe.sid}: observer died: {e!r}"
                )
                return

    def _watch_once(self) -> None:
        from gol_tpu.distributed import wire

        sock = socket.create_connection(self._address, timeout=10)
        try:
            # A small receive buffer makes reader stalls reach the
            # server's writer queue quickly (the kernel can't absorb
            # the backlog for us).
            with contextlib.suppress(OSError):
                sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF,
                                self._rcvbuf)
            sock.settimeout(10)
            hello = {"t": "hello", "want_flips": True,
                     "role": "observe", "session": self._recipe.sid}
            if self._secret is not None:
                hello["secret"] = self._secret
            wire.send_msg(sock, hello)
            msgs = 0
            while not self._halt.is_set():
                msg = wire.recv_msg(sock, allow_binary=False)
                if msg is None:
                    return
                t = msg.get("t")
                if t == "error":
                    # unknown-session right after a crash restart —
                    # resume may still be materializing it.
                    return
                if t == "bye":
                    return
                if t == "board":
                    turn, board = wire.msg_to_board(msg)
                    self._shadow = np.array(board, np.uint8)
                    self._shadow_turn = turn
                    self.syncs += 1
                    self._check(turn, "BoardSync")
                elif t == "flips" and self._shadow is not None:
                    turn, coords = wire.msg_flips_array(msg)
                    if turn > self._shadow_turn and len(coords):
                        xy = np.asarray(coords).reshape(-1, 2)
                        self._shadow[xy[:, 1], xy[:, 0]] ^= np.uint8(255)
                        self._shadow_turn = turn
                msgs += 1
                if msgs % self._stall_every == 0:
                    # The chaos ingredient: wedge our own reader. The
                    # server must degrade us (shed + coalesce), never
                    # corrupt us — the next BoardSync's bit-check is
                    # the judge.
                    self.stalls += 1
                    if self._halt.wait(
                        self._stall_secs * (0.5 + self._rng.random())
                    ):
                        return
        finally:
            with contextlib.suppress(OSError):
                sock.close()

    def final_check(self) -> None:
        """Verify the last applied state once more (call after stop;
        flips-built states between syncs get judged too)."""
        if self._shadow is not None and self._shadow_turn >= 0:
            self._check(self._shadow_turn, "final shadow")


class ChaosRunner:
    """The full subprocess scenario: a REAL `--serve --sessions`
    process, seeded verb storms + stalled observers against it,
    SIGKILL at a seeded verb count (mid-storm, so verbs are genuinely
    in flight), restart with `--resume latest` on the same port, and
    the end-state judgement. One seed drives every draw. Returns the
    report dict on success; raises ChaosError with the full complaint
    list otherwise.

    `tests/test_chaos.py::test_chaos_sigkill_storm_resume` runs it
    small; `scripts/chaos_smoke.sh` runs it as a shell-visible smoke
    (`python -m gol_tpu.testing.chaos`)."""

    def __init__(self, *, seed: int, workdir: str,
                 image_dir: str = "fixtures/images",
                 storms: int = 2, verbs_per_storm: int = 12,
                 kills: int = 1, stall_secs: float = 1.0,
                 fault_spec: Optional[str] = None,
                 max_sessions: Optional[int] = None,
                 boot_timeout: float = 120.0,
                 settle_timeout: float = 240.0):
        import os

        self._rng = random.Random(seed)
        self.seed = seed
        self.workdir = workdir
        self.out_dir = os.path.join(workdir, "out")
        self.image_dir = os.path.abspath(image_dir)
        self.storms_n = storms
        self.verbs_per_storm = verbs_per_storm
        self.kills = kills
        self.stall_secs = stall_secs
        self.fault_spec = fault_spec
        self.max_sessions = max_sessions
        self.boot_timeout = boot_timeout
        self.settle_timeout = settle_timeout
        self._proc = None
        self._log_path = None
        self._boot = 0
        self._verb_count = 0
        self._verb_lock = threading.Lock()
        self.metrics_port: Optional[int] = None

    # --- server process management ---

    def _spawn_server(self, port: int, resume: bool):
        import os
        import subprocess
        import sys

        self._boot += 1
        self._log_path = f"{self.workdir}/server-{self._boot}.log"
        cmd = [sys.executable, "-m", "gol_tpu",
               "-w", "64", "-h", "64", "-t", "1", "-noVis",
               "--platform", "cpu",
               "--serve", f"127.0.0.1:{port}", "--sessions",
               "--images", self.image_dir, "--out", self.out_dir,
               "--autosave-turns", "64",
               "--hb-secs", "0.5", "--metrics-port", "0",
               "--check-invariants",
               "--high-water", "24", "--drain-secs", "6"]
        if self.max_sessions is not None:
            cmd += ["--max-sessions", str(self.max_sessions)]
        if resume:
            cmd += ["--resume", "latest"]
        env = dict(os.environ)
        # The child runs with cwd=workdir (its out/ tree must not
        # litter the repo): put the repo on its import path instead.
        import gol_tpu

        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(gol_tpu.__file__)
        ))
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        # Chaos always runs with the deadlock/leak detector armed: a
        # fault schedule that drives the server into a lock-order cycle
        # or a held-too-long stall must fail the run, not hang it.
        env.setdefault("GOL_TPU_LOCKCHECK", "1")
        if self.fault_spec:
            env["GOL_TPU_FAULTS"] = self.fault_spec
        log = open(self._log_path, "w")
        self._proc = subprocess.Popen(
            cmd, stdout=log, stderr=subprocess.STDOUT, env=env,
            cwd=self.workdir,
        )
        self._await_banner()

    def _await_banner(self) -> None:
        deadline = time.monotonic() + self.boot_timeout
        serving = mport = None
        while time.monotonic() < deadline:
            if self._proc.poll() is not None:
                raise ChaosError(
                    f"server died during boot — see {self._log_path}"
                )
            with open(self._log_path) as f:
                for line in f:
                    if "session engine serving on" in line:
                        serving = line
                    if "metrics serving on" in line:
                        mport = int(
                            line.rsplit(":", 1)[1].split("/", 1)[0]
                        )
            if serving and mport:
                self.metrics_port = mport
                return
            time.sleep(0.2)
        raise ChaosError(f"server never bound — see {self._log_path}")

    def _sigkill_server(self) -> None:
        import signal

        self._proc.send_signal(signal.SIGKILL)
        self._proc.wait(timeout=30)

    def _stop_server(self) -> None:
        import signal

        if self._proc is None or self._proc.poll() is not None:
            return
        self._proc.send_signal(signal.SIGTERM)
        try:
            self._proc.wait(timeout=30)
        except Exception:
            self._proc.kill()
            self._proc.wait(timeout=10)

    def _fetch_metrics(self) -> str:
        import urllib.request

        with urllib.request.urlopen(
            f"http://127.0.0.1:{self.metrics_port}/metrics", timeout=10
        ) as r:
            return r.read().decode()

    # --- the scenario ---

    def _count_verb(self) -> None:
        with self._verb_lock:
            self._verb_count += 1

    def run(self) -> dict:
        from gol_tpu.distributed.client import SessionControl
        from gol_tpu.io.pgm import read_pgm

        port = _free_port()
        address = ("127.0.0.1", port)
        self._spawn_server(port, resume=False)
        complaints: "list[str]" = []
        report: dict = {"seed": self.seed, "kills": 0}
        storms: "list[VerbStorm]" = []
        observers: "list[ShadowObserver]" = []
        try:
            # Pinned sessions: never destroyed, watched by the
            # stalled observers — the degradation + bit-identity
            # probes of the scenario.
            # Fat boards for the pinned pair: their per-turn flip
            # frames are big enough that a stalled reader reaches the
            # writer-queue high-water mark (drives degradation).
            pinned = [
                Recipe(f"pin-{i}", width=192, height=192,
                       seed=self._rng.randrange(2 ** 31),
                       density=0.25 + 0.1 * self._rng.random())
                for i in range(2)
            ]
            boot_ctl = SessionControl(*address, timeout=15.0,
                                      retry_window=60.0,
                                      retry_seed=self.seed)
            for r in pinned:
                boot_ctl.create(r.sid, **r.create_kwargs())
            for i, r in enumerate(pinned):
                ob = ShadowObserver(address, r,
                                    seed=self._rng.randrange(2 ** 31),
                                    stall_secs=self.stall_secs,
                                    stall_every=30 + 10 * i)
                ob.start()
                observers.append(ob)
            for i in range(self.storms_n):
                st = VerbStorm(address,
                               seed=self._rng.randrange(2 ** 31),
                               prefix=f"storm{i}",
                               verbs=self.verbs_per_storm,
                               retry_window=120.0,
                               on_verb=self._count_verb)
                st.start()
                storms.append(st)

            # SIGKILL at a seeded verb count — genuinely mid-storm.
            total_verbs = self.storms_n * self.verbs_per_storm
            for k in range(self.kills):
                lo = (k + 1) * total_verbs // (self.kills + 1)
                kill_at = max(1, lo - self._rng.randrange(3))
                deadline = time.monotonic() + self.settle_timeout
                while (self._verb_count < kill_at
                       and any(s.is_alive() for s in storms)
                       and time.monotonic() < deadline):
                    time.sleep(0.05)
                if not any(s.is_alive() for s in storms):
                    break  # storms already done: kill would be idle
                self._sigkill_server()
                report["kills"] += 1
                self._spawn_server(port, resume=True)

            deadline = time.monotonic() + self.settle_timeout
            for s in storms:
                s.join(max(1.0, deadline - time.monotonic()))
                if s.is_alive():
                    complaints.append(f"storm {s.name} never finished")
                elif s.error is not None:
                    complaints.append(
                        f"storm {s.name} failed: {s.error!r}"
                    )
            for ob in observers:
                ob.stop()
            for ob in observers:
                ob.join(15.0)

            # --- judgement ---
            ctl = SessionControl(*address, timeout=15.0,
                                 retry_window=60.0,
                                 retry_seed=self.seed + 1)
            live = {s["id"] for s in ctl.list()}
            expected: "dict[str, Recipe]" = {
                r.sid: r for r in pinned
            }
            destroyed: "set[str]" = set()
            for s in storms:
                expected.update(s.alive)
                destroyed |= s.destroyed
            destroyed -= set(expected)
            if live != set(expected):
                complaints.append(
                    f"live sessions {sorted(live)} != ledger "
                    f"{sorted(expected)} (duplicates or losses)"
                )
            resurrected = live & destroyed
            if resurrected:
                complaints.append(
                    f"destroyed sessions resurrected: "
                    f"{sorted(resurrected)}"
                )
            verified = 0
            for sid in sorted(live & set(expected)):
                r = expected[sid]
                cp = ctl.checkpoint(sid)
                got = read_pgm(cp["path"])
                want = oracle_board(r, int(cp["turn"]))
                if not np.array_equal(got != 0, want != 0):
                    complaints.append(
                        f"{sid}: board at turn {cp['turn']} differs "
                        f"from the unfaulted run"
                    )
                else:
                    verified += 1
            for ob in observers:
                ob.final_check()
                complaints.extend(ob.errors)
            metrics = self._fetch_metrics()
            violations = parse_metric(
                metrics, "gol_tpu_invariant_violations_total"
            )
            if violations:
                complaints.append(
                    f"{int(violations)} invariant violation(s) on the "
                    f"server"
                )
            lock_reports = parse_metric(
                metrics, "gol_tpu_lockcheck_violations_total"
            )
            if lock_reports:
                complaints.append(
                    f"{int(lock_reports)} lockcheck report(s) on the "
                    f"server (lock-order cycle or held-too-long)"
                )
            report.update(
                verbs=self._verb_count,
                sessions_verified=verified,
                live=sorted(live),
                destroyed=sorted(destroyed),
                observer_syncs=sum(ob.syncs for ob in observers),
                observer_stalls=sum(ob.stalls for ob in observers),
                observer_verified_turn=max(
                    (ob.verified_turn for ob in observers), default=0
                ),
                degradations=parse_metric(
                    metrics, "gol_tpu_server_degradations_total"
                ),
                recoveries=parse_metric(
                    metrics, "gol_tpu_server_degraded_recoveries_total"
                ),
                invariant_violations=int(violations),
                lockcheck_violations=int(lock_reports),
            )
            ctl.close()
            boot_ctl.close()
        finally:
            for ob in observers:
                ob.stop()
            self._stop_server()
        if complaints:
            raise ChaosError(
                f"chaos seed {self.seed}: " + "; ".join(complaints)
            )
        return report


def _free_port() -> int:
    s = socket.create_server(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def main(argv=None) -> int:
    """`python -m gol_tpu.testing.chaos --seed N` — the shell entry
    `scripts/chaos_smoke.sh` drives; prints the report as JSON."""
    import argparse
    import json
    import tempfile

    ap = argparse.ArgumentParser(prog="gol_tpu.testing.chaos")
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--storms", type=int, default=2)
    ap.add_argument("--verbs", type=int, default=12)
    ap.add_argument("--kills", type=int, default=1)
    ap.add_argument("--faults", default=None,
                    help="GOL_TPU_FAULTS spec for the server process")
    ap.add_argument("--max-sessions", type=int, default=None)
    args = ap.parse_args(argv)
    workdir = args.workdir or tempfile.mkdtemp(prefix="gol-chaos-")
    runner = ChaosRunner(seed=args.seed, workdir=workdir,
                         storms=args.storms,
                         verbs_per_storm=args.verbs,
                         kills=args.kills, fault_spec=args.faults,
                         max_sessions=args.max_sessions)
    report = runner.run()
    print(json.dumps(report, indent=2, sort_keys=True))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
