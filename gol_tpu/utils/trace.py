"""Tracing & profiling — the analog of the reference's runtime/trace
harness (ref: trace_test.go:12-29, artifact trace.out, inspected with
`go tool trace` per README.md:89).

Two complementary layers:

- `device_trace(dir)`: wraps `jax.profiler.trace` — captures XLA/TPU
  device activity into a Perfetto/TensorBoard trace directory, the
  direct stand-in for trace.out (view with Perfetto instead of
  `go tool trace`).
- `Timeline`: a lock-free host-side span recorder the engine feeds one
  record per device dispatch (chunk of turns). Where the Go trace shows
  goroutine spawn/steal patterns of the per-turn worker farm
  (ref: gol/distributor.go:116-173), this shows the engine's dispatch
  cadence: turns per chunk, dispatch wall time, turns/sec — queryable
  in-process and dumpable to JSON for offline analysis.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import json
import time
from typing import Iterator, Optional

import jax

from gol_tpu.obs import atomic_write_text


@contextlib.contextmanager
def device_trace(trace_dir: str) -> Iterator[None]:
    """Capture a device profile for the enclosed block (trace.out analog)."""
    with jax.profiler.trace(trace_dir):
        yield


@dataclasses.dataclass(frozen=True)
class Span:
    """One engine dispatch: `turns` turns committed ending at `turn`."""

    turn: int
    turns: int
    seconds: float
    kind: str  # "chunk" (fused fori_loop) or "diff" (per-turn with flips)

    @property
    def turns_per_sec(self) -> float:
        return self.turns / self.seconds if self.seconds > 0 else float("inf")


class Timeline:
    """Per-dispatch span RING. Appends are single-writer (engine thread);
    reads take a snapshot copy, so no lock is needed (the reference's
    ticker read its turn counter unlocked and raced, SURVEY.md §2; here
    the deque append is atomic under the GIL and readers never mutate).

    Past `capacity` the OLDEST spans are evicted — a long run keeps the
    recent window instead of silently freezing at the run's first
    `capacity` dispatches (the old drop-at-capacity behavior meant an
    infinite-run profile showed only its warm-up). `summary()` reports
    `dropped` so a truncated window is always visible, and the totals
    keep counting every recorded span, evicted or not."""

    def __init__(self, capacity: int = 100_000):
        self.capacity = capacity
        self._spans: "collections.deque[Span]" = collections.deque(
            maxlen=capacity
        )
        self._t0 = time.perf_counter()
        # Running totals over EVERY recorded span (eviction is a memory
        # bound, not an accounting one).
        self._recorded = 0
        self._total_turns = 0
        self._total_seconds = 0.0

    # -- engine side --

    def record(self, turn: int, turns: int, seconds: float, kind: str) -> None:
        self._recorded += 1
        self._total_turns += turns
        self._total_seconds += seconds
        self._spans.append(Span(turn, turns, seconds, kind))

    # -- reader side --

    @property
    def spans(self) -> list[Span]:
        return list(self._spans)

    @property
    def dropped(self) -> int:
        """Spans evicted from the ring (recorded minus retained)."""
        return max(0, self._recorded - len(self._spans))

    def summary(self) -> dict:
        total_turns = self._total_turns
        total_s = self._total_seconds
        return {
            "dispatches": self._recorded,
            "retained": len(self._spans),
            "dropped": self.dropped,
            "turns": total_turns,
            "busy_seconds": round(total_s, 6),
            "wall_seconds": round(time.perf_counter() - self._t0, 6),
            "turns_per_sec": round(total_turns / total_s, 1) if total_s else None,
        }

    def dump(self, path: str) -> None:
        # Crash-safe (temp file + rename): a killed engine never leaves
        # a truncated timeline artifact behind.
        atomic_write_text(
            path,
            json.dumps(
                {"summary": self.summary(),
                 "spans": [dataclasses.asdict(s) for s in self.spans]},
            ),
        )


def profile_run(params, trace_dir: Optional[str] = None, **engine_kwargs):
    """Run one engine to completion under a Timeline (and optionally a
    device trace), returning (engine, timeline) — the TestTrace analog
    as a library call (ref: trace_test.go:12-29)."""
    from gol_tpu.engine.distributor import Engine

    timeline = Timeline()
    engine = Engine(params, timeline=timeline, **engine_kwargs)
    ctx = device_trace(trace_dir) if trace_dir else contextlib.nullcontext()
    with ctx:
        engine.run()
    return engine, timeline
