"""The test-facing coordinate type (ref: util/cell.go:4-6).

`x` is the column, `y` is the row — the convention of the reference's
`calculateAliveCells` (ref: gol/distributor.go:420-432). This framework
uses that one convention everywhere, eliminating the reference's
axis-swap quirks (SURVEY.md §2 "Known behavioral quirks")."""

from typing import NamedTuple


class Cell(NamedTuple):
    x: int
    y: int


def cells_from_mask(arr) -> "list[Cell]":
    """Coordinates of nonzero entries of a (H, W) array as Cell(x=col, y=row).

    The single conversion point between array indexing (row, col) and the
    test-facing Cell convention — keep it unique so the contract cannot
    diverge between event payloads and fixture loaders."""
    import numpy as np

    ys, xs = np.nonzero(np.asarray(arr))
    return [Cell(int(x), int(y)) for x, y in zip(xs, ys)]


def xy_from_mask(arr) -> "object":
    """Nonzero coordinates of a (H, W) array as an (N, 2) int32 ndarray
    of (x, y) pairs — the vectorized form of `cells_from_mask`, in the
    SAME row-major order (events.FlipBatch payloads)."""
    import numpy as np

    ys, xs = np.nonzero(np.asarray(arr))
    return np.column_stack([xs, ys]).astype(np.int32)
