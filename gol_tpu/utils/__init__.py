from gol_tpu.utils.cell import Cell
from gol_tpu.utils.check import check

__all__ = ["Cell", "check"]
