"""ASCII board rendering for test-failure diagnostics — the analog of the
reference's side-by-side box-drawing diff (ref: util/visualise.go:21-108).

Given two alive-cell sets (got vs want) of a small board, renders them
side by side with box-drawing borders, marking cells present in only one
set so a failing golden test shows *where* the boards diverge."""

from __future__ import annotations

from typing import Iterable, Sequence

from gol_tpu.utils.cell import Cell

_ALIVE = "█"
_DEAD = " "
_ONLY_HERE = "◆"  # alive here, dead in the other board


def board_lines(
    alive: Iterable[Cell], width: int, height: int, other: Iterable[Cell] | None = None
) -> list[str]:
    """Render one board as a list of strings, one per row, boxed.

    Cells alive in `alive` but not in `other` (when given) are marked
    with a diff glyph (ref: util/visualise.go:50-69 marks mismatches)."""
    alive_set = set(alive)
    other_set = set(other) if other is not None else None
    top = "┌" + "─" * width + "┐"
    bot = "└" + "─" * width + "┘"
    lines = [top]
    for y in range(height):
        row = []
        for x in range(width):
            c = Cell(x, y)
            if c in alive_set:
                if other_set is not None and c not in other_set:
                    row.append(_ONLY_HERE)
                else:
                    row.append(_ALIVE)
            else:
                row.append(_DEAD)
        lines.append("│" + "".join(row) + "│")
    lines.append(bot)
    return lines


def alive_cells_to_string(
    got: Sequence[Cell],
    want: Sequence[Cell],
    width: int,
    height: int,
) -> str:
    """Side-by-side "got | want" ASCII diff (ref: util/visualise.go:21-48,
    used by the golden test on 16x16 failures, ref: gol_test.go:49-56)."""
    left = board_lines(got, width, height, other=want)
    right = board_lines(want, width, height, other=got)
    header = f"{'GOT':^{width + 2}}   {'WANT':^{width + 2}}"
    body = "\n".join(f"{l}   {r}" for l, r in zip(left, right))
    return header + "\n" + body
