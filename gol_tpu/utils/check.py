"""Error escalation + board assertion helpers (ref: util/check.go:3-7,
board multiset compare ref: gol_test.go:58-86)."""

from typing import Iterable


def check(err):
    """Raise if `err` is an exception / truthy error value."""
    if isinstance(err, BaseException):
        raise err
    if err:
        raise RuntimeError(str(err))


def assert_equal_board(got: Iterable, want: Iterable, width: int, height: int):
    """Alive-cell set equality with an ASCII side-by-side diff for small
    boards on failure — the reference's assertEqualBoard + 16x16 diff
    rendering (ref: gol_test.go:49-86, util/visualise.go:21-48)."""
    got_set, want_set = set(got), set(want)
    if got_set == want_set:
        return
    msg = [f"boards differ: {len(got_set)} alive, expected {len(want_set)}"]
    if width <= 64 and height <= 64:
        from gol_tpu.utils.visualise import alive_cells_to_string

        msg.append(alive_cells_to_string(sorted(got_set), sorted(want_set),
                                         width, height))
    else:
        only_got = sorted(got_set - want_set)[:10]
        only_want = sorted(want_set - got_set)[:10]
        msg.append(f"first extra cells: {only_got}")
        msg.append(f"first missing cells: {only_want}")
    raise AssertionError("\n".join(msg))
