"""Error escalation helper (ref: util/check.go:3-7)."""


def check(err):
    """Raise if `err` is an exception / truthy error value."""
    if isinstance(err, BaseException):
        raise err
    if err:
        raise RuntimeError(str(err))
