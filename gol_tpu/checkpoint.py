"""Checkpoint discovery — PGM snapshots as the fault-tolerance store.

A PGM snapshot is a complete checkpoint: the board is the whole state
and the turn number is encoded in the filename `<W>x<H>x<T>.pgm`
(filename convention ref: gol/distributor.go:181,230; PGM-as-checkpoint
per SURVEY.md §5 "Checkpoint / resume"). The reference's fault-tolerance
extension (ref: README.md:261-265) asks for runs that survive component
death; here that is: periodic engine-side autosaves (Params.autosave_*),
crash-atomic writes (io/pgm.py), and these helpers to find the newest
complete checkpoint to resume from.
"""

from __future__ import annotations

import json
import os
import re
from typing import Optional

_SNAP = re.compile(r"^(\d+)x(\d+)x(\d+)\.pgm$")

#: Basename of the per-session-tree tombstone a destroy leaves behind
#: (docs/SESSIONS.md "Crash-consistent resume"): resume discovery
#: treats a tombstoned session directory as destroyed even when the
#: manifest rewrite that normally records the destroy never landed
#: (SIGKILL between the two writes).
TOMBSTONE = ".tombstone"


def record_resume_turn(turn: int) -> None:
    """Publish the turn this process resumed from as the
    `gol_tpu_resume_turn` gauge (0 = fresh start) — the one
    registration point every resume path (local CLI, EngineServer)
    shares, so the smoke harness and operators read a single series.
    Imported lazily: checkpoint discovery itself stays stdlib-only."""
    from gol_tpu import obs

    obs.gauge(
        "gol_tpu_resume_turn",
        "Turn this process resumed from (0 = fresh start)",
    ).set(turn)


def snapshot_turn(path: str | os.PathLike) -> int:
    """Turn number encoded in a snapshot filename `<W>x<H>x<T>.pgm`."""
    m = _SNAP.match(os.path.basename(os.fspath(path)))
    if not m:
        raise ValueError(f"not a snapshot filename: {path!r}")
    return int(m.group(3))


def session_checkpoint_dir(out_dir: str | os.PathLike) -> str:
    """Root of the per-session checkpoint tree: each session owns
    `<out>/sessions/<id>/` holding its `<W>x<H>x<T>.pgm` snapshots and
    a `session.json` sidecar (rule + geometry — the PGM filename alone
    cannot carry the ruleset). Layout: docs/SESSIONS.md."""
    return os.path.join(os.fspath(out_dir), "sessions")


def session_manifest_path(out_dir: str | os.PathLike) -> str:
    """The session set's commit record: `<out>/sessions/manifest.json`,
    rewritten crash-atomically (temp + rename) at every create/destroy.
    Resume trusts the manifest over the directory listing — a crashed
    process may leave half-written session trees, but the manifest names
    exactly the set that was live at the last completed verb."""
    return os.path.join(session_checkpoint_dir(out_dir), "manifest.json")


def read_session_manifest(out_dir: str | os.PathLike) -> Optional[dict]:
    """The manifest's `sessions` mapping (sid -> {width, height, rule,
    seed?, density?}), or None when it is missing, torn, or not the
    expected shape — a truncated manifest on a freshly crashed tree is
    "no manifest" (fall back to the directory scan), never an
    exception."""
    try:
        with open(session_manifest_path(out_dir)) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    sessions = data.get("sessions") if isinstance(data, dict) else None
    if not isinstance(sessions, dict):
        return None
    return {
        sid: meta for sid, meta in sessions.items()
        if isinstance(sid, str) and isinstance(meta, dict)
    }


def manifest_parked(meta) -> bool:
    """True when a manifest session entry records a PARKED (hibernated)
    session — checkpointed, device rows freed, rehydrated bit-exactly
    on the next attach (docs/SESSIONS.md "Hibernation"). The one
    spelling of the flag, shared by the manager's writer and resume
    discovery: a parked entry carries `parked: true` plus the `turn`
    its snapshot encodes, alongside the ordinary recipe fields."""
    return bool(isinstance(meta, dict) and meta.get("parked"))


def tombstone_path(out_dir: str | os.PathLike, sid: str) -> str:
    """Per-session destroy marker `<out>/sessions/<sid>/.tombstone` —
    written BEFORE the manifest rewrite, so every crash window between
    the two leaves the session provably destroyed, never resurrected."""
    return os.path.join(session_checkpoint_dir(out_dir), sid, TOMBSTONE)


def is_tombstoned(out_dir: str | os.PathLike, sid: str) -> bool:
    """True when `sid` carries a destroy tombstone. Only existence
    matters: a truncated (even empty) tombstone still records the
    destroy — the content is operator forensics, not protocol."""
    return os.path.exists(tombstone_path(out_dir, sid))


def latest_any_snapshot(
    snap_dir: str | os.PathLike,
) -> Optional[tuple[str, int, int]]:
    """(path, width, height) of the highest-turn snapshot of ANY
    geometry in `snap_dir`, or None. The per-session variant of
    `latest_snapshot`: a session directory's geometry is whatever its
    snapshots say, so discovery cannot pre-filter on W x H. Same
    determinism contract: sorted listing, lexicographic tie-break,
    unreadable dir = no checkpoint."""
    best_turn, best = -1, None
    try:
        names = sorted(os.listdir(snap_dir))
    except OSError:
        return None
    for name in names:
        m = _SNAP.match(name)
        if not m:
            continue
        w, h, turn = (int(g) for g in m.groups())
        if turn > best_turn:
            best_turn = turn
            best = (os.path.join(os.fspath(snap_dir), name), w, h)
    return best


def latest_snapshot(
    out_dir: str | os.PathLike, width: int, height: int
) -> Optional[str]:
    """Path of the highest-turn `<W>x<H>x<T>.pgm` in `out_dir`, or None.

    Only complete snapshots are visible: in-flight writes live under a
    dotted `.tmp` name until their atomic rename, so a run killed
    mid-write never offers a truncated board here. An unreadable (or
    missing) directory is "no checkpoint", never an exception — resume
    discovery runs on freshly crashed trees.

    Ties (two filenames encoding the same turn, e.g. a zero-padded
    `64x64x0100.pgm` next to `64x64x100.pgm`) resolve to the
    lexicographically first name: discovery must be deterministic
    across runs, and os.listdir order is not.
    """
    best_turn, best = -1, None
    try:
        names = sorted(os.listdir(out_dir))
    except OSError:
        return None
    for name in names:
        m = _SNAP.match(name)
        if not m:
            continue
        w, h, turn = (int(g) for g in m.groups())
        if (w, h) == (width, height) and turn > best_turn:
            best_turn, best = turn, os.path.join(os.fspath(out_dir), name)
    return best
