"""Checkpoint discovery — PGM snapshots as the fault-tolerance store.

A PGM snapshot is a complete checkpoint: the board is the whole state
and the turn number is encoded in the filename `<W>x<H>x<T>.pgm`
(filename convention ref: gol/distributor.go:181,230; PGM-as-checkpoint
per SURVEY.md §5 "Checkpoint / resume"). The reference's fault-tolerance
extension (ref: README.md:261-265) asks for runs that survive component
death; here that is: periodic engine-side autosaves (Params.autosave_*),
crash-atomic writes (io/pgm.py), and these helpers to find the newest
complete checkpoint to resume from.
"""

from __future__ import annotations

import os
import re
from typing import Optional

_SNAP = re.compile(r"^(\d+)x(\d+)x(\d+)\.pgm$")


def record_resume_turn(turn: int) -> None:
    """Publish the turn this process resumed from as the
    `gol_tpu_resume_turn` gauge (0 = fresh start) — the one
    registration point every resume path (local CLI, EngineServer)
    shares, so the smoke harness and operators read a single series.
    Imported lazily: checkpoint discovery itself stays stdlib-only."""
    from gol_tpu import obs

    obs.gauge(
        "gol_tpu_resume_turn",
        "Turn this process resumed from (0 = fresh start)",
    ).set(turn)


def snapshot_turn(path: str | os.PathLike) -> int:
    """Turn number encoded in a snapshot filename `<W>x<H>x<T>.pgm`."""
    m = _SNAP.match(os.path.basename(os.fspath(path)))
    if not m:
        raise ValueError(f"not a snapshot filename: {path!r}")
    return int(m.group(3))


def session_checkpoint_dir(out_dir: str | os.PathLike) -> str:
    """Root of the per-session checkpoint tree: each session owns
    `<out>/sessions/<id>/` holding its `<W>x<H>x<T>.pgm` snapshots and
    a `session.json` sidecar (rule + geometry — the PGM filename alone
    cannot carry the ruleset). Layout: docs/SESSIONS.md."""
    return os.path.join(os.fspath(out_dir), "sessions")


def latest_any_snapshot(
    snap_dir: str | os.PathLike,
) -> Optional[tuple[str, int, int]]:
    """(path, width, height) of the highest-turn snapshot of ANY
    geometry in `snap_dir`, or None. The per-session variant of
    `latest_snapshot`: a session directory's geometry is whatever its
    snapshots say, so discovery cannot pre-filter on W x H. Same
    determinism contract: sorted listing, lexicographic tie-break,
    unreadable dir = no checkpoint."""
    best_turn, best = -1, None
    try:
        names = sorted(os.listdir(snap_dir))
    except OSError:
        return None
    for name in names:
        m = _SNAP.match(name)
        if not m:
            continue
        w, h, turn = (int(g) for g in m.groups())
        if turn > best_turn:
            best_turn = turn
            best = (os.path.join(os.fspath(snap_dir), name), w, h)
    return best


def latest_snapshot(
    out_dir: str | os.PathLike, width: int, height: int
) -> Optional[str]:
    """Path of the highest-turn `<W>x<H>x<T>.pgm` in `out_dir`, or None.

    Only complete snapshots are visible: in-flight writes live under a
    dotted `.tmp` name until their atomic rename, so a run killed
    mid-write never offers a truncated board here. An unreadable (or
    missing) directory is "no checkpoint", never an exception — resume
    discovery runs on freshly crashed trees.

    Ties (two filenames encoding the same turn, e.g. a zero-padded
    `64x64x0100.pgm` next to `64x64x100.pgm`) resolve to the
    lexicographically first name: discovery must be deterministic
    across runs, and os.listdir order is not.
    """
    best_turn, best = -1, None
    try:
        names = sorted(os.listdir(out_dir))
    except OSError:
        return None
    for name in names:
        m = _SNAP.match(name)
        if not m:
            continue
        w, h, turn = (int(g) for g in m.groups())
        if (w, h) == (width, height) and turn > best_turn:
            best_turn, best = turn, os.path.join(os.fspath(out_dir), name)
    return best
