"""Checkpoint discovery — PGM snapshots as the fault-tolerance store.

A PGM snapshot is a complete checkpoint: the board is the whole state
and the turn number is encoded in the filename `<W>x<H>x<T>.pgm`
(filename convention ref: gol/distributor.go:181,230; PGM-as-checkpoint
per SURVEY.md §5 "Checkpoint / resume"). The reference's fault-tolerance
extension (ref: README.md:261-265) asks for runs that survive component
death; here that is: periodic engine-side autosaves (Params.autosave_*),
crash-atomic writes (io/pgm.py), and these helpers to find the newest
complete checkpoint to resume from.
"""

from __future__ import annotations

import os
import re
from typing import Optional

_SNAP = re.compile(r"^(\d+)x(\d+)x(\d+)\.pgm$")


def snapshot_turn(path: str | os.PathLike) -> int:
    """Turn number encoded in a snapshot filename `<W>x<H>x<T>.pgm`."""
    m = _SNAP.match(os.path.basename(os.fspath(path)))
    if not m:
        raise ValueError(f"not a snapshot filename: {path!r}")
    return int(m.group(3))


def latest_snapshot(
    out_dir: str | os.PathLike, width: int, height: int
) -> Optional[str]:
    """Path of the highest-turn `<W>x<H>x<T>.pgm` in `out_dir`, or None.

    Only complete snapshots are visible: in-flight writes live under a
    dotted `.tmp` name until their atomic rename, so a run killed
    mid-write never offers a truncated board here.
    """
    best_turn, best = -1, None
    try:
        names = os.listdir(out_dir)
    except OSError:
        return None
    for name in names:
        m = _SNAP.match(name)
        if not m:
            continue
        w, h, turn = (int(g) for g in m.groups())
        if (w, h) == (width, height) and turn > best_turn:
            best_turn, best = turn, os.path.join(os.fspath(out_dir), name)
    return best
