"""Byte-exact PGM (P5) codec.

The reference streams pixels one byte per Go-channel send through a
long-lived IO goroutine (ref: gol/io.go:66-74,119-123) — a deliberate
coursework bottleneck. The TPU-native design does whole-array reads and
writes instead; what is preserved byte-for-byte is the on-disk format:

    P5\n<W> <H>\n255\n<row-major raster, one byte per cell, 0 or 255>

(writer ref: gol/io.go:52-59,76-81; reader validation ref:
gol/io.go:100-116; verified against every fixture under
/root/reference/images and /root/reference/check/images).
"""

from __future__ import annotations

import contextlib
import os

import numpy as np

from gol_tpu.utils.cell import Cell, cells_from_mask

MAGIC = b"P5"
MAXVAL = 255


def read_pgm(path: str | os.PathLike) -> np.ndarray:
    """Read a P5 PGM into a (H, W) uint8 array with values in {0, 255}.

    Header validation mirrors the reference reader: magic must be P5 and
    maxval must be 255 (ref: gol/io.go:100-116). Unlike the reference —
    which tokenises the whole file with strings.Fields and would corrupt
    rasters containing whitespace bytes (ref: gol/io.go:98-119, safe there
    only because GoL pixels are 0x00/0xFF) — this parser splits only the
    three header fields and treats the rest as binary raster.
    """
    with open(path, "rb") as f:
        data = f.read()

    # Header is exactly three whitespace-terminated fields: magic,
    # "W H", maxval. Comments (#) are not produced by the reference
    # writer but are legal P5; skip them.
    pos = 0
    fields: list[bytes] = []
    while len(fields) < 4:
        # skip whitespace
        while pos < len(data) and data[pos : pos + 1].isspace():
            pos += 1
        if pos >= len(data):
            raise ValueError(f"{path}: truncated pgm header")
        if data[pos : pos + 1] == b"#":
            while pos < len(data) and data[pos] != 0x0A:
                pos += 1
            continue
        start = pos
        while pos < len(data) and not data[pos : pos + 1].isspace():
            pos += 1
        fields.append(data[start:pos])
    pos += 1  # single whitespace byte after maxval, then raster begins

    magic, w_s, h_s, maxval_s = fields
    if magic != MAGIC:
        raise ValueError(f"{path}: not a P5 pgm (magic={magic!r})")
    width, height = int(w_s), int(h_s)
    if int(maxval_s) != MAXVAL:
        raise ValueError(f"{path}: maxval {maxval_s!r} != 255")

    if len(data) - pos < width * height:
        raise ValueError(f"{path}: truncated raster")
    raster = np.frombuffer(data, dtype=np.uint8, count=width * height, offset=pos)
    return raster.reshape(height, width).copy()


def encode_pgm(world: np.ndarray) -> bytes:
    """Serialise a (H, W) uint8 world to reference-identical P5 bytes
    (header format ref: gol/io.go:52-59)."""
    world = np.asarray(world, dtype=np.uint8)
    h, w = world.shape
    return b"P5\n%d %d\n255\n" % (w, h) + world.tobytes()


def write_pgm(path: str | os.PathLike, world: np.ndarray) -> None:
    """Write the world to `path`, creating parent dirs (the reference
    mkdirs `out/`, ref: gol/io.go:43) and fsyncing (ref: gol/io.go:83).

    The write is crash-atomic: bytes land in a same-directory temp file
    that is `os.replace`d over the target only after the fsync. PGM
    snapshots double as checkpoints (SURVEY.md §5), so a process killed
    mid-write must never leave a truncated board under a name the
    resume path would trust. (The reference writes in place,
    ref: gol/io.go:48-87 — a kill mid-write there corrupts the file.)"""
    path = os.fspath(path)
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    tmp = os.path.join(parent, f".{os.path.basename(path)}.tmp")
    try:
        with open(tmp, "wb") as f:
            f.write(encode_pgm(world))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        # Failed writes (ENOSPC, EIO) must not accumulate orphan temp
        # files across a long autosave run.
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise


def alive_cells_from_pgm(path: str | os.PathLike) -> list[Cell]:
    """Golden-fixture loader: the alive-cell set of a PGM, as Cell(x, y)
    (the analog of the test harness's readAliveCells,
    ref: gol_test.go:88-129)."""
    return cells_from_mask(read_pgm(path))
