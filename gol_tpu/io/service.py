"""Asynchronous storage I/O service — the analog of the reference's
long-lived IO goroutine (ref: gol/io.go:129-149).

The reference streams pixels one byte per channel send and offers three
verbs: output, input, check-idle (ref: gol/io.go:35-39). This service
keeps the architecture — I/O off the engine thread, an idle handshake
before shutdown (ref: gol/distributor.go:200-203) — but moves whole
arrays at once, so a 512×512 snapshot is one file write instead of
262,144 channel sends. Writes are async (the turn loop never stalls on
disk); reads are synchronous.
"""

from __future__ import annotations

import logging
import os
import queue
import threading
from typing import Callable, Optional

import numpy as np

from gol_tpu.io.pgm import read_pgm, write_pgm


class IOService:
    """Background thread executing read/write commands from a queue
    (command-queue architecture ref: gol/io.go:12-19,129-149)."""

    def __init__(self, image_dir: str = "images", out_dir: str = "out"):
        self.image_dir = image_dir
        self.out_dir = out_dir
        self._cmds: queue.Queue = queue.Queue()
        self._thread = threading.Thread(target=self._loop, name="gol-io", daemon=True)
        self._thread.start()

    # --- verbs (ref: gol/io.go ioCommand enum) ---

    def read(self, name: str) -> np.ndarray:
        """Synchronous image load from `<image_dir>/<name>.pgm`
        (ref: gol/io.go:90-126)."""
        reply: queue.Queue = queue.Queue()
        self._cmds.put(("read", name, reply))
        result = reply.get()
        if isinstance(result, BaseException):
            raise result
        return result

    def write(
        self,
        name: str,
        world: np.ndarray,
        on_complete: Optional[Callable[[str, Optional[BaseException]], None]] = None,
    ) -> None:
        """Asynchronous image write to `<out_dir>/<name>.pgm`
        (ref: gol/io.go:42-87). `on_complete(name, exc)` fires on the IO
        thread once the bytes are synced (exc=None) or the write failed —
        the hook the engine uses to emit `ImageOutputComplete` without
        blocking the turn loop."""
        self._cmds.put(("write", name, np.asarray(world, dtype=np.uint8), on_complete))

    def check_idle(self) -> bool:
        """Block until all queued commands have drained — the shutdown
        handshake (ref: gol/distributor.go:200-203, gol/io.go:144-147)."""
        reply: queue.Queue = queue.Queue()
        self._cmds.put(("idle", reply))
        return reply.get()

    def stop(self) -> None:
        self._cmds.put(("stop",))
        self._thread.join(timeout=5)

    # --- internals ---

    def _loop(self) -> None:
        while True:
            cmd = self._cmds.get()
            verb = cmd[0]
            if verb == "read":
                _, name, reply = cmd
                try:
                    reply.put(read_pgm(os.path.join(self.image_dir, f"{name}.pgm")))
                except BaseException as e:  # surfaced on the caller thread
                    reply.put(e)
            elif verb == "write":
                _, name, world, on_complete = cmd
                exc: Optional[BaseException] = None
                try:
                    write_pgm(os.path.join(self.out_dir, f"{name}.pgm"), world)
                except BaseException as e:
                    # The service must survive ENOSPC/EROFS etc. — a dead
                    # IO thread would hang every later read/check_idle.
                    exc = e
                if on_complete is not None:
                    try:
                        on_complete(name, exc)
                    except BaseException:
                        # A raising callback must not kill the service —
                        # but it must not vanish without a trace either.
                        logging.getLogger(__name__).exception(
                            "IO on_complete callback failed for %r", name
                        )
            elif verb == "idle":
                cmd[1].put(True)
            elif verb == "stop":
                return
