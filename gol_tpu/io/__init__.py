from gol_tpu.io.pgm import read_pgm, write_pgm, alive_cells_from_pgm

__all__ = ["read_pgm", "write_pgm", "alive_cells_from_pgm"]
