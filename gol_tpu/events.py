"""Typed event protocol — the public contract between engine, tests and
visualiser, re-designed from the reference's `gol/event.go`.

Six concrete event types mirror the reference exactly
(ref: gol/event.go:19-68); stringification rules mirror the reference's
Stringer set so a log consumer prints the same lines the SDL loop would
(ref: gol/event.go:72-131 — CellFlipped/TurnComplete/FinalTurnComplete
stringify to "" and are therefore never logged, ref: sdl/loop.go:44-47).

Turn numbering: `completed_turns` is the number of *fully committed*
turns, 1-based after the first turn — the convention the golden CSV uses
(check/alive/512x512.csv row 1 == after turn 1). The reference's counter
was 0-based-and-racy (ref: gol/distributor.go:94,118,294 vs
gol/event.go:12-14); this framework fixes the race and keeps the
CSV-compatible observable.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import List

import numpy as np

from gol_tpu.utils.cell import Cell


class State(enum.Enum):
    """Engine execution state (ref: gol/event.go:34-45)."""

    PAUSED = 0
    EXECUTING = 1
    QUITTING = 2

    def __str__(self) -> str:  # ref: gol/event.go:110-121
        return self.name.capitalize()


@dataclasses.dataclass(frozen=True)
class Event:
    """Base event; every event reports how many turns were complete when it
    was emitted (ref: gol/event.go:9-15)."""

    completed_turns: int

    def __str__(self) -> str:
        return ""


@dataclasses.dataclass(frozen=True)
class AliveCellsCount(Event):
    """Periodic telemetry: number of alive cells (ref: gol/event.go:19-22),
    emitted by the ticker every `tick_seconds` (ref: gol/distributor.go:290-295)."""

    cells_count: int = 0

    def __str__(self) -> str:  # ref: gol/event.go:72-75
        return f"{self.cells_count} Cells Alive"


@dataclasses.dataclass(frozen=True)
class ImageOutputComplete(Event):
    """A PGM image write finished (ref: gol/event.go:26-29)."""

    filename: str = ""

    def __str__(self) -> str:  # ref: gol/event.go:78-81
        return f"File {self.filename} output complete"


@dataclasses.dataclass(frozen=True)
class StateChange(Event):
    """Engine switched execution state (ref: gol/event.go:32-45)."""

    new_state: State = State.EXECUTING

    def __str__(self) -> str:  # ref: gol/event.go:84-87
        return f"State change to {self.new_state}"


@dataclasses.dataclass(frozen=True)
class CellFlipped(Event):
    """One cell changed state this turn (ref: gol/event.go:50-53). Emitted
    for every initially-alive cell before turn 1 (ref: gol/distributor.go:72-80)
    and for every cell whose state changed on each committed turn
    (ref: gol/distributor.go:212-220). Never logged (empty string)."""

    cell: Cell = Cell(0, 0)


@dataclasses.dataclass(frozen=True, eq=False)
class FlipBatch(Event):
    """Framework extension (no reference analog): one turn's flipped
    cells as a single (N, 2) int32 array of (x, y) pairs in row-major
    board order — semantically identical to N CellFlipped events.
    Opt-in (`Engine(emit_flip_batches=True)`): the per-cell stream is
    the reference contract, but a watched 512² board flips thousands
    of cells per turn and per-cell Python event objects cap the whole
    watched pipeline at ~30 turns/s; the server, wire and visualiser
    consume batches vectorized instead. Never logged."""

    # np.ndarray (N, 2) int32 of (x, y); the default is a valid empty
    # batch so a payload-less construction cannot poison consumers.
    cells: "object" = dataclasses.field(
        default_factory=lambda: np.zeros((0, 2), np.int32)
    )
    # Optional (N,) uint8 gray levels of the listed cells (the
    # Generations family's injective PGM levels). None = two-state
    # batch, applied as an XOR; with levels the batch SETS each cell's
    # level — the multi-state visual contract (r5: gray-level gens
    # visualisation, no more forced-headless carve-out).
    levels: "object" = None


@dataclasses.dataclass(frozen=True, eq=False)
class FlipChunk(Event):
    """Framework extension (no reference analog): a whole k-turn diff
    chunk as ONE event — the chunk-granular emit path behind the
    batched wire (ROADMAP item 1). Covers turns
    `first_turn .. completed_turns` inclusive; per-turn changed
    packed words ride in the device compact layout: `counts[t]`
    changed words for turn `first_turn + t`, their positions as the
    changed-word `bitmaps` row (uint32, bit i of word w = packed word
    w*32+i changed — the wire.grid_words convention), and the words'
    XOR `words` masks concatenated across turns in ascending word
    order per turn. Semantically identical to k FlipBatch events each
    followed by its TurnComplete; opt-in
    (`Engine(emit_flip_chunks=True)`) because at 10⁵ turns/s the
    per-turn Python event objects ARE the bottleneck — consumers
    (the wire broadcaster) expand per turn only for peers that still
    need per-turn delivery. Never logged."""

    first_turn: int = 0
    # (k,) int changed-word counts per turn.
    counts: "object" = dataclasses.field(
        default_factory=lambda: np.zeros(0, np.int64)
    )
    # (k, nb) uint32 changed-word bitmaps, one row per turn.
    bitmaps: "object" = dataclasses.field(
        default_factory=lambda: np.zeros((0, 0), np.uint32)
    )
    # (Σcounts,) uint32 changed-word XOR masks.
    words: "object" = dataclasses.field(
        default_factory=lambda: np.zeros(0, np.uint32)
    )


@dataclasses.dataclass(frozen=True)
class TurnComplete(Event):
    """A turn was committed (ref: gol/event.go:58-60). The visualiser
    renders on this (ref: sdl/loop.go:38-40). Never logged."""


@dataclasses.dataclass(frozen=True)
class FinalTurnComplete(Event):
    """The run finished; carries the complete alive-cell set — the payload
    the golden tests assert on (ref: gol/event.go:65-68, gol_test.go:36-41)."""

    alive: List[Cell] = dataclasses.field(default_factory=list)


@dataclasses.dataclass(frozen=True, eq=False)
class BoardSync(Event):
    """Framework extension (no reference analog): a full host copy of the
    committed world, emitted by the engine when a controller attaches
    mid-run. Riding the event stream — not a side channel — is what makes
    the attach sync ordered against per-turn CellFlipped diffs: BoardSync
    at turn N is always followed by flips for N+1, never overlapped.
    Plays the role of the reference's commented GetCurrentBoard RPC
    (ref: gol/distributor.go:489-498). Never logged (empty string).

    `token` identifies the requester, so a sync queued for a subscriber
    that vanished before it was serviced is dropped instead of being
    delivered to the next subscriber."""

    world: "object" = None  # np.ndarray (H, W) {0,255}
    token: int = 0
