"""Crash-atomic controller manifest: the controller's WAL.

Everything the controller cannot afford to forget across a SIGKILL
lives here — two-phase migration records, the registry of nodes IT
spawned (so a restarted controller re-adopts its children instead of
double-spawning), and roll progress. One JSON file, rewritten whole
through `obs.atomic_write_text` (temp + fsync + rename), exactly the
session manifest's durability discipline: a torn write is impossible,
a missing file means "fresh controller".

Migration records are the load-bearing part. Each is

    {"sid": S, "src": A, "dst": B, "phase": "intent"|"done"|"aborted",
     "serving": ADDR|null, "reason": str|null}

keyed by a stable rid `mig-<sid>-<seq>`. The controller writes
`intent` BEFORE touching engine A, and `done`/`aborted` only AFTER
the fleet reflects the outcome. A controller killed between the two
finds the `intent` at boot and re-drives the same legs — every leg
verb (park / adopt / destroy) is state-based idempotent on the engine
side, so re-driving converges instead of duplicating.
"""

from __future__ import annotations

import copy
import os
import json
from typing import Dict, List, Optional

from gol_tpu import obs
from gol_tpu.analysis.concurrency import lockcheck

__all__ = ["ControllerManifest"]

_PHASES = ("intent", "done", "aborted")


class ControllerManifest:
    """Durable controller state at `path`. Every mutator persists
    before returning — callers may treat a returned mutation as
    survived-a-SIGKILL."""

    def __init__(self, path: "str | os.PathLike"):
        self.path = os.fspath(path)
        self._lock = lockcheck.make_lock("ControllerManifest._lock")
        self._state = self._load()

    # -- persistence ------------------------------------------------------

    def _load(self) -> dict:
        try:
            with open(self.path) as f:
                raw = json.load(f)
        except (OSError, ValueError):
            # Missing or torn (pre-rename crash leaves the OLD file, so
            # "torn" here really means hand-edited garbage): start fresh.
            raw = {}
        if not isinstance(raw, dict):
            raw = {}
        state = {
            "seq": int(raw.get("seq", 0) or 0),
            "migrations": {},
            "spawned": {"relays": {}, "engines": {}},
            "roll": {"generation": 0, "done": []},
        }
        migs = raw.get("migrations")
        if isinstance(migs, dict):
            for rid, rec in migs.items():
                if (isinstance(rec, dict)
                        and rec.get("phase") in _PHASES
                        and isinstance(rec.get("sid"), str)):
                    state["migrations"][str(rid)] = {
                        "sid": rec["sid"],
                        "src": rec.get("src"),
                        "dst": rec.get("dst"),
                        "phase": rec["phase"],
                        "serving": rec.get("serving"),
                        "reason": rec.get("reason"),
                    }
        spawned = raw.get("spawned")
        if isinstance(spawned, dict):
            for kind in ("relays", "engines"):
                nodes = spawned.get(kind)
                if isinstance(nodes, dict):
                    for listen, meta in nodes.items():
                        if isinstance(meta, dict):
                            state["spawned"][kind][str(listen)] = {
                                "metrics": meta.get("metrics"),
                                "pid": meta.get("pid"),
                            }
        roll = raw.get("roll")
        if isinstance(roll, dict):
            state["roll"] = {
                "generation": int(roll.get("generation", 0) or 0),
                "done": [a for a in roll.get("done", [])
                         if isinstance(a, str)],
            }
        return state

    def _persist_locked(self) -> None:
        obs.atomic_write_text(
            self.path, json.dumps(self._state, indent=1, sort_keys=True))

    # -- migrations (two-phase) -------------------------------------------

    def migration_begin(self, sid: str, src: str, dst: str) -> str:
        """Record intent and return the migration's rid. Re-begun for a
        sid that already has an open intent, returns THAT rid — the
        resume path after a controller crash, not a new migration."""
        with self._lock:
            for rid, rec in self._state["migrations"].items():
                if rec["sid"] == sid and rec["phase"] == "intent":
                    return rid
            self._state["seq"] += 1
            rid = f"mig-{sid}-{self._state['seq']}"
            self._state["migrations"][rid] = {
                "sid": sid, "src": src, "dst": dst,
                "phase": "intent", "serving": src, "reason": None,
            }
            self._persist_locked()
            return rid

    def migration_done(self, rid: str, serving: str) -> None:
        with self._lock:
            rec = self._state["migrations"].get(rid)
            if rec is None:
                raise KeyError(rid)
            rec["phase"] = "done"
            rec["serving"] = serving
            self._persist_locked()

    def migration_abort(self, rid: str, reason: str) -> None:
        with self._lock:
            rec = self._state["migrations"].get(rid)
            if rec is None:
                raise KeyError(rid)
            rec["phase"] = "aborted"
            rec["reason"] = reason
            self._persist_locked()

    def migration(self, rid: str) -> Optional[dict]:
        with self._lock:
            rec = self._state["migrations"].get(rid)
            return copy.deepcopy(rec) if rec is not None else None

    def pending_migrations(self) -> Dict[str, dict]:
        """Open intents (rid -> record), the crash-resume worklist."""
        with self._lock:
            return {rid: copy.deepcopy(rec)
                    for rid, rec in self._state["migrations"].items()
                    if rec["phase"] == "intent"}

    def serving(self, sid: str) -> Optional[str]:
        """Where the newest migration record says `sid` is served, or
        None if no migration ever touched it."""
        with self._lock:
            best = None
            for rid, rec in self._state["migrations"].items():
                if rec["sid"] == sid:
                    best = rec  # insertion order == seq order
            return best["serving"] if best else None

    # -- spawned-node registry --------------------------------------------

    def record_spawn(self, kind: str, listen: str,
                     metrics: Optional[str], pid: Optional[int]) -> None:
        with self._lock:
            self._state["spawned"][kind][listen] = {
                "metrics": metrics, "pid": pid}
            self._persist_locked()

    def forget_spawn(self, kind: str, listen: str) -> None:
        with self._lock:
            if self._state["spawned"][kind].pop(listen, None) is not None:
                self._persist_locked()

    def spawned(self, kind: str) -> Dict[str, dict]:
        with self._lock:
            return copy.deepcopy(self._state["spawned"][kind])

    # -- roll progress ----------------------------------------------------

    def roll_state(self) -> dict:
        with self._lock:
            return copy.deepcopy(self._state["roll"])

    def roll_start(self, generation: int) -> None:
        """Reset progress for a new generation (no-op if already on
        it, preserving mid-roll progress across controller restarts)."""
        with self._lock:
            if self._state["roll"]["generation"] != generation:
                self._state["roll"] = {"generation": generation,
                                       "done": []}
                self._persist_locked()

    def roll_mark(self, addr: str) -> None:
        with self._lock:
            if addr not in self._state["roll"]["done"]:
                self._state["roll"]["done"].append(addr)
                self._persist_locked()

    def roll_done(self) -> List[str]:
        with self._lock:
            return list(self._state["roll"]["done"])
