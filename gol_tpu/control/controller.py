"""The reconciling fleet controller (docs/CONTROL.md).

One level-triggered loop: scrape observed state (the SAME
`gol_tpu.obs.scrape` join the console renders), diff it against the
declarative `FleetSpec`, and apply at most `actions_per_round`
corrective verbs — heal, roll, migrate, scale, in that priority order
(a dead relay starves observers NOW; an over-provisioned tree merely
wastes a process). The loop never remembers what it "already did":
every round re-derives its worklist from observation plus the
crash-atomic `ControllerManifest`, so a controller SIGKILLed between
any two statements resumes by reconciling, not by replaying a journal.

Safety rules every verb obeys:

- **budget** — at most `actions_per_round` verbs per round; work left
  over waits for the next round (`budget_exhausted_total` counts the
  rounds that clipped).
- **staleness** — a destructive verb (kill, park, destroy, drain) is
  refused unless the evidence endpoint answered a scrape within
  `stale_secs` (`stale_refusals_total`); acting on a stale picture is
  how controllers kill healthy nodes.
- **backoff** — a failing action key retries under seeded-jitter
  exponential backoff (the PR 3 discipline), so a flapping alert
  cannot spawn-storm the host.
- **drain-then-kill** — a retiring relay's children are re-pointed
  first and the retiree is killed only once a FRESH scrape observes
  zero peers; a rolling engine is drained (checkpoint-all + refuse new
  session attaches) before its SIGTERM, and comes back behind
  `--resume latest` + coalesced BoardSync.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import random
import re
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.request
from typing import Dict, List, Optional, Tuple

from gol_tpu import obs
from gol_tpu.analysis.concurrency import lockcheck
from gol_tpu.control.manifest import ControllerManifest
from gol_tpu.control.spec import EngineSpec, FleetSpec
from gol_tpu.distributed import wire
from gol_tpu.obs import flight, tracing
from gol_tpu.obs.scrape import Endpoint, fleet_snapshot

log = logging.getLogger(__name__)

__all__ = ["Controller", "engine_cost", "repoint_relay"]

_RELAY_BANNER = re.compile(
    r"relay serving on ([\w.-]+:\d+) \(upstream [\w.-]+:\d+\)"
)
_ENGINE_BANNER = re.compile(r"session engine serving on ([\w.-]+:\d+)")
_METRICS_BANNER = re.compile(r"metrics serving on http://([\w.-]+:\d+)")


def repoint_relay(addr: str, new_upstream: str,
                  secret: Optional[str] = None,
                  timeout: float = 10.0) -> dict:
    """Send the `repoint` verb to a relay's DOWNSTREAM listener: dial,
    hello (binary — the relay tier's capability floor), wait for the
    attach-ack, issue the verb, and read frames until the `repoint-r`
    answer (board syncs and heartbeats ride the same link and are
    skipped). Raises WireError on a reasoned rejection; OSError family
    on link failures — the caller's backoff owns retries."""
    from gol_tpu.testing import faults

    host, _, port = str(addr).rpartition(":")
    sock = faults.wrap("client", socket.create_connection(
        (host, int(port)), timeout=timeout
    ))
    try:
        sock.settimeout(timeout)
        hello = {"t": "hello", "binary": True, "want_flips": False,
                 "role": "observe"}
        if secret is not None:
            hello["secret"] = secret
        wire.send_msg(sock, hello)
        deadline = time.monotonic() + timeout
        while True:
            if time.monotonic() > deadline:
                raise wire.WireError("repoint verb timed out")
            msg = wire.recv_msg(sock)
            if msg is None:
                raise wire.WireError("relay closed before repoint-r")
            t = msg.get("t")
            if t == "error":
                raise wire.WireError(
                    f"relay rejected: {msg.get('reason', 'rejected')}"
                )
            if t == "attach-ack":
                wire.send_msg(sock, {"t": "repoint",
                                     "addr": new_upstream})
            elif t == "repoint-r":
                if not msg.get("ok"):
                    raise wire.WireError(
                        f"repoint refused: {msg.get('reason')}"
                    )
                return msg
            # board / fbatch / hb / clk frames: not ours, skip.
    finally:
        with contextlib.suppress(OSError):
            sock.close()


def engine_cost(out_dir: str) -> float:
    """One engine's attributable load, read from its crash-safe usage
    ledgers (accounting plane, <out>/usage): the seconds-denominated
    resources summed across every principal — time an engine spent
    working for tenants is the comparable currency across engines
    (FLOPs and wire bytes scale with board geometry, not load). An
    absent or torn ledger reads as 0: a fresh engine is the cheapest
    by definition, which is exactly where a new session belongs."""
    from gol_tpu.obs import accounting

    totals = accounting.read_ledger(os.path.join(out_dir, "usage"))
    cost = 0.0
    for res in totals.values():
        for key in ("dispatch_seconds", "host_seconds",
                    "queue_frame_seconds"):
            try:
                cost += float(res.get(key, 0.0) or 0.0)
            except (TypeError, ValueError):
                continue
    return cost


class _CtlMetrics:
    def __init__(self, spec_name: str):
        obs.gauge(
            "gol_tpu_controller_info",
            "Controller identity (value 1): which spec this process "
            "reconciles — obs.console decorates its fleet row with it",
            {"spec": spec_name},
        ).set(1)
        self.desired = obs.gauge(
            "gol_tpu_controller_desired_nodes",
            "Node count the spec wants (relays wanted by the scale "
            "rule + declared engines)",
        )
        self.observed = obs.gauge(
            "gol_tpu_controller_observed_nodes",
            "Node count the last reconcile round actually observed up",
        )
        self.rounds = obs.counter(
            "gol_tpu_controller_rounds_total",
            "Reconcile rounds completed (scrape + diff + actions)",
        )
        self.budget_exhausted = obs.counter(
            "gol_tpu_controller_budget_exhausted_total",
            "Rounds that still had corrective work after spending the "
            "actions_per_round budget",
        )
        self.stale_refusals = obs.counter(
            "gol_tpu_controller_stale_refusals_total",
            "Destructive actions refused because the evidence scrape "
            "was older than stale_secs",
        )
        self.scale_source = {
            src: obs.counter(
                "gol_tpu_controller_scale_decisions_total",
                "Scale-rule evaluations by evidence source: 'history' "
                "(canary turn-age queried from the collector, "
                "sustained over canary_for_secs) or 'peers' (live "
                "peer-count fallback)",
                {"source": src},
            ) for src in ("history", "peers")
        }
        self.last_heal = obs.gauge(
            "gol_tpu_controller_last_heal_seconds",
            "Wall seconds the most recent heal took: dead-relay "
            "detection confirmed -> replacement spawned -> orphan "
            "subtree re-pointed (the control_heal bench lane)",
        )
        self._actions: Dict[Tuple[str, str], object] = {}

    def action(self, verb: str, outcome: str) -> None:
        key = (verb, outcome)
        c = self._actions.get(key)
        if c is None:
            c = obs.counter(
                "gol_tpu_controller_actions_total",
                "Corrective verbs applied by the reconcile loop, by "
                "verb (heal/scale/migrate/roll/spawn) and outcome "
                "(ok/error)",
                {"verb": verb, "outcome": outcome},
            )
            self._actions[key] = c
        c.inc()


class Controller:
    """The reconcile loop over one `FleetSpec`. `reconcile_once` is
    the whole control plane — `start()` merely repeats it on
    `spec.interval_secs`; tests drive it directly (optionally with an
    injected snapshot, so every refusal path is unit-testable without
    a process mesh)."""

    def __init__(self, spec: FleetSpec, *, out_dir: str,
                 seed: Optional[int] = None):
        self.spec = spec
        self.out_dir = os.fspath(out_dir)
        os.makedirs(self.out_dir, exist_ok=True)
        self.manifest = ControllerManifest(
            os.path.join(self.out_dir, "controller.json"))
        self._rng = random.Random(seed)
        self._metrics = _CtlMetrics(os.path.basename(spec.path))
        self._lock = lockcheck.make_lock("Controller._lock")
        #: spec string -> Endpoint (persistent: rates need prev samples).
        self._endpoints: Dict[str, Endpoint] = {}
        for s in spec.scrape:
            self._endpoints[s] = Endpoint(s)
        for e in spec.engines:
            if e.metrics is not None:
                self._endpoints.setdefault(e.metrics, Endpoint(e.metrics))
        #: Last OBSERVED identity per endpoint spec — what we still
        #: know about a node after it stops answering (heal needs the
        #: dead relay's listen + upstream).
        self._ident: Dict[str, dict] = {}
        self._last_ok: Dict[str, float] = {}
        self._down: Dict[str, int] = {}
        #: action key -> (attempt, not-before monotonic).
        self._backoff: Dict[str, Tuple[int, float]] = {}
        #: Relays mid-retirement (listen addrs): children re-pointed,
        #: waiting for an observed-zero-peers scrape before the kill.
        self._retiring: set = set()
        self._procs: Dict[str, subprocess.Popen] = {}
        self._ctls: Dict[str, object] = {}
        self._shutdown = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.rounds = 0
        self.last_summary: dict = {}
        # Re-adopt spawned nodes from a previous incarnation: their
        # metrics endpoints re-enter the scrape set (Popen children
        # survive a controller SIGKILL; the manifest remembers them).
        for kind in ("relays", "engines"):
            for listen, meta in self.manifest.spawned(kind).items():
                if meta.get("metrics"):
                    self._endpoints.setdefault(meta["metrics"],
                                               Endpoint(meta["metrics"]))

    # --- lifecycle (the relay/server idiom) ---

    def start(self) -> "Controller":
        t = threading.Thread(target=self._run_loop,
                             name="gol-control-reconcile", daemon=True)
        t.start()
        self._thread = t
        return self

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._shutdown.wait(timeout)

    def shutdown(self) -> None:
        """Stop reconciling. Spawned fleet processes are LEFT RUNNING
        — a control-plane restart must never take the data plane down
        with it (the manifest lets the next incarnation re-adopt
        them)."""
        self._shutdown.set()
        for ctl in self._ctls.values():
            with contextlib.suppress(Exception):
                ctl.close()
        self._ctls.clear()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def _run_loop(self) -> None:
        while not self._shutdown.is_set():
            try:
                self.reconcile_once()
            except Exception:
                # The loop IS the product: one broken round must never
                # end reconciliation (level-triggered — next round
                # re-observes from scratch).
                log.exception("reconcile round failed")
            self._shutdown.wait(self.spec.interval_secs)

    def health(self) -> dict:
        with self._lock:
            return {
                "mode": "control",
                "spec": self.spec.path,
                "rounds": self.rounds,
                "retiring": sorted(self._retiring),
                "pending_migrations":
                    len(self.manifest.pending_migrations()),
                "last_round": dict(self.last_summary),
            }

    # --- the round ---

    def reconcile_once(self, snapshot: Optional[dict] = None,
                       now: Optional[float] = None) -> dict:
        """One level-triggered round. Returns the summary dict (also
        kept as `last_summary` for /healthz). `snapshot` injects a
        pre-built `fleet_snapshot` result (tests); `now` pins the
        staleness clock."""
        if now is None:
            now = time.monotonic()
        if snapshot is None:
            snapshot = fleet_snapshot(list(self._endpoints.values()))
            # fleet_snapshot just scraped: every up row is fresh NOW.
            for row in snapshot["rows"]:
                if row.get("up"):
                    self._last_ok[row["endpoint"]] = now
        rows = [r for r in snapshot.get("rows", []) if r.get("up")]
        down_specs = set(snapshot.get("down", []))
        self._observe(rows, down_specs)

        actions: List[dict] = []
        actions += self._plan_heal(rows, now)
        actions += self._plan_roll(rows, now)
        actions += self._plan_migrate(now)
        actions += self._plan_scale(rows, snapshot.get("tree", []), now)

        budget = self.spec.actions_per_round
        applied, deferred, refused = [], 0, 0
        for action in actions:
            if budget <= 0:
                self._metrics.budget_exhausted.inc()
                break
            key = action["key"]
            attempt, not_before = self._backoff.get(key, (0, 0.0))
            if now < not_before:
                deferred += 1
                continue
            if action.get("evidence") is not None and not self._fresh(
                action["evidence"], now
            ):
                self._metrics.stale_refusals.inc()
                refused += 1
                continue
            budget -= 1
            try:
                action["fn"]()
            except Exception as e:
                self._metrics.action(action["verb"], "error")
                delay = min(2.0, 0.05 * (2 ** min(attempt, 10)))
                delay *= 0.5 + self._rng.random()
                self._backoff[key] = (attempt + 1, now + delay)
                log.warning("action %s failed: %s", key, e)
                flight.note("control.action_failed", key=key,
                            error=str(e))
                applied.append({"key": key, "verb": action["verb"],
                                "ok": False, "error": str(e)})
            else:
                self._metrics.action(action["verb"], "ok")
                self._backoff.pop(key, None)
                applied.append({"key": key, "verb": action["verb"],
                                "ok": True})

        desired = (self._want_relays(rows)
                   + len(self.spec.engines))
        observed = len(rows)
        self._metrics.desired.set(desired)
        self._metrics.observed.set(observed)
        self._metrics.rounds.inc()
        summary = {
            "desired": desired, "observed": observed,
            "planned": len(actions), "applied": applied,
            "deferred": deferred, "stale_refused": refused,
            "budget_left": budget,
        }
        with self._lock:
            self.rounds += 1
            self.last_summary = summary
        tracing.event("control.round", "lifecycle",
                      planned=len(actions), applied=len(applied))
        return summary

    def _observe(self, rows: List[dict], down_specs: set) -> None:
        for row in rows:
            spec_str = row["endpoint"]
            self._down[spec_str] = 0
            if row.get("listen"):
                self._ident[spec_str] = {
                    "listen": row["listen"],
                    "upstream": row.get("upstream"),
                    "relay": row.get("upstream") is not None,
                }
        for spec_str in down_specs:
            self._down[spec_str] = self._down.get(spec_str, 0) + 1

    def _fresh(self, spec_str: str, now: float) -> bool:
        last = self._last_ok.get(spec_str)
        return last is not None and (now - last) <= self.spec.stale_secs

    # --- heal ---

    def _plan_heal(self, rows: List[dict], now: float) -> List[dict]:
        actions = []
        spawned_relays = self.manifest.spawned("relays")
        spawned_engines = self.manifest.spawned("engines")
        handled = set()
        for spec_str, misses in sorted(self._down.items()):
            if misses < self.spec.down_rounds:
                continue
            ident = self._ident.get(spec_str)
            if ident is None:
                # An endpoint that never answered carries no identity
                # to heal around; engines are matched below by their
                # declared metrics spec instead.
                eng = self._engine_by_metrics(spec_str)
                if eng is not None and eng.spawn:
                    actions.append(self._heal_engine_action(eng))
                    handled.add(eng.addr)
                continue
            if ident["relay"]:
                listen = ident["listen"]
                if listen in self._retiring:
                    continue  # dying on purpose
                actions.append({
                    "verb": "heal", "key": f"heal:{listen}",
                    "evidence": None,  # the evidence IS the absence
                    "fn": lambda s=spec_str, i=ident, r=rows:
                        self._heal_relay(s, i, r),
                })
            else:
                eng = self._engine_by_metrics(spec_str)
                if eng is not None and eng.spawn:
                    actions.append(self._heal_engine_action(eng))
                    handled.add(eng.addr)
        # Alert-driven heal: a relay that still answers scrapes but
        # has one of the spec's heal alerts firing (turn-age SLO blown
        # = the node forwards nothing useful) is replaced the same way.
        if self.spec.heal_alerts:
            want = set(self.spec.heal_alerts)
            for row in rows:
                if row.get("upstream") is None:
                    continue
                if row["listen"] in self._retiring:
                    continue
                if want & set(row.get("alerts") or ()):
                    ident = {"listen": row["listen"],
                             "upstream": row.get("upstream"),
                             "relay": True}
                    actions.append({
                        "verb": "heal",
                        "key": f"heal:{row['listen']}",
                        "evidence": row["endpoint"],
                        "fn": lambda s=row["endpoint"], i=ident, r=rows:
                            self._heal_relay(s, i, r),
                    })
        # Managed engines never seen at all (first boot): spawn them.
        for eng in self.spec.engines:
            if not eng.spawn or eng.addr in handled:
                continue
            if eng.addr in spawned_engines or eng.addr in self._procs:
                continue
            if eng.metrics is not None and self._last_ok.get(eng.metrics):
                continue  # answered at least once: it exists
            actions.append({
                "verb": "spawn", "key": f"spawn:{eng.addr}",
                "evidence": None,
                "fn": lambda e=eng: self._spawn_engine(e),
            })
        # Spawned relays whose record outlived the process (pid gone,
        # endpoint down): drop the registry entry so scale re-counts.
        for listen, meta in spawned_relays.items():
            pid = meta.get("pid")
            if pid and not _pid_alive(pid):
                m = meta.get("metrics")
                if m is None or self._down.get(m, 0) > 0:
                    self.manifest.forget_spawn("relays", listen)
                    self._retiring.discard(listen)
        return actions

    def _engine_by_metrics(self, spec_str: str) -> Optional[EngineSpec]:
        for e in self.spec.engines:
            if e.metrics == spec_str:
                return e
        return None

    def _heal_engine_action(self, eng: EngineSpec) -> dict:
        return {
            "verb": "heal", "key": f"heal-engine:{eng.addr}",
            "evidence": None,
            "fn": lambda e=eng: self._spawn_engine(e),
        }

    def _heal_relay(self, spec_str: str, ident: dict,
                    rows: List[dict]) -> None:
        """Replace one dead relay: spawn a fresh `--relay` on the dead
        node's upstream, then re-point every orphaned child at the
        replacement. Bit-exactness is the data plane's job — each
        re-pointed child re-attaches with a fresh BoardSync and its
        leaves ride the PR 3 reconnect."""
        t0 = time.monotonic()
        dead_listen = ident["listen"]
        upstream = ident.get("upstream") or self.spec.root
        listen, metrics = self._spawn_relay(upstream)
        orphans = [r for r in rows
                   if r.get("upstream") == dead_listen
                   and r.get("listen") != listen]
        for child in orphans:
            repoint_relay(child["listen"], listen,
                          secret=self.spec.secret)
        # The dead node's books: registry entry, scrape endpoint,
        # identity — all retired with it.
        self.manifest.forget_spawn("relays", dead_listen)
        self._endpoints.pop(spec_str, None)
        self._ident.pop(spec_str, None)
        self._down.pop(spec_str, None)
        self._last_ok.pop(spec_str, None)
        took = time.monotonic() - t0
        self._metrics.last_heal.set(took)
        log.info("healed relay %s -> %s (%d orphans re-pointed, "
                 "%.2fs)", dead_listen, listen, len(orphans), took)
        tracing.event("control.heal", "lifecycle", dead=dead_listen,
                      replacement=listen, orphans=len(orphans))
        flight.note("control.heal", dead=dead_listen,
                    replacement=listen, seconds=round(took, 3))

    # --- scale ---

    def _want_relays(self, rows: List[dict]) -> int:
        """The scale rule: enough relays that no one carries more than
        `observers_per_relay` downstreams, clamped to [min, max]."""
        observers = 0.0
        for r in rows:
            if r.get("upstream") is not None:
                observers += (r.get("relay_peers") or 0)
                observers += (r.get("ws_peers") or 0)
            elif r.get("listen"):
                observers += (r.get("peers") or 0)
        want = -(-int(observers) // int(self.spec.observers_per_relay))
        return max(self.spec.relay_min,
                   min(self.spec.relay_max, want))

    def _canary_age_points(self) -> Optional[List[Tuple[float, float]]]:
        """The canary's MEASURED turn-age history over the trailing
        `canary_for_secs` window, queried from the collector's /query
        API: [(ts, age)], newest last — or None when no collector is
        configured or the query fails (the caller falls back to the
        live peer-count rule)."""
        if self.spec.collector is None \
                or self.spec.canary_max_age_s is None:
            return None
        window = max(2.0, self.spec.canary_for_secs)
        step = max(0.5, window / 8.0)
        url = (f"http://{self.spec.collector}/query"
               f"?expr=max(gol_tpu_client_turn_age_seconds)"
               f"&start=-{window}&end=-0&step={step}")
        try:
            with urllib.request.urlopen(url, timeout=2.0) as r:
                payload = json.loads(r.read())
            return [(float(p[0]), float(p[1]))
                    for p in payload["series"][0]["points"]
                    if p[1] is not None]
        except Exception as e:
            log.warning("collector query failed (%s): falling back "
                        "to the peer-count scale rule", e)
            return None

    def _want_relays_from_history(self, have: int) -> Optional[int]:
        """The SLO-history scale rule: grow when the canary's queried
        turn age breached `canary_max_age_s` for the WHOLE window
        (every recorded point — one noisy scrape holds, it never
        pages a spawn), shrink when the whole window sat in deep
        comfort (< 1/4 of the SLO). Anything in between — including a
        window with too few points to judge — holds the current count.
        None = no usable history; use the peer-count rule."""
        points = self._canary_age_points()
        if points is None or len(points) < 2:
            return None
        max_age = self.spec.canary_max_age_s
        values = [v for _, v in points]
        lo, hi = self.spec.relay_min, self.spec.relay_max
        if all(v > max_age for v in values):
            return max(lo, min(hi, have + 1))
        if all(v < 0.25 * max_age for v in values):
            return max(lo, min(hi, have - 1))
        return max(lo, min(hi, have))

    def _plan_scale(self, rows: List[dict], tree: List[dict],
                    now: float) -> List[dict]:
        actions = []
        live_relays = [r for r in rows
                       if r.get("upstream") is not None
                       and r["listen"] not in self._retiring]
        have = len(live_relays)
        want = self._want_relays_from_history(have)
        if want is not None:
            self._metrics.scale_source["history"].inc()
        else:
            want = self._want_relays(rows)
            self._metrics.scale_source["peers"].inc()
        # A node mid-debounce (missed a scrape but not yet confirmed
        # dead by down_rounds) makes `have` ambiguous: growing against
        # that dip double-provisions — the node either comes back (the
        # grow was spurious) or is confirmed dead and HEALED (the
        # replacement fills the same slot). Hold growth until the
        # picture settles; shrink/kill are already evidence-gated.
        ambiguous = any(
            0 < misses < self.spec.down_rounds
            for spec_str, misses in self._down.items()
            if self._ident.get(spec_str, {}).get("relay")
        )
        if have < want and not ambiguous:
            for i in range(want - have):
                actions.append({
                    "verb": "scale", "key": f"scale:grow:{i}",
                    "evidence": None,
                    "fn": lambda: self._grow(),
                })
        elif have > want:
            actions += self._plan_shrink(rows, have - want, now)
        # Retiring relays drained to zero observed peers on a FRESH
        # scrape: finish the kill.
        for row in rows:
            listen = row.get("listen")
            if listen not in self._retiring:
                continue
            if (row.get("relay_peers") or 0) == 0 \
                    and (row.get("ws_peers") or 0) == 0:
                actions.append({
                    "verb": "scale", "key": f"scale:kill:{listen}",
                    "evidence": row["endpoint"],
                    "fn": lambda l=listen, s=row["endpoint"]:
                        self._kill_retired(l, s),
                })
        return actions

    def _plan_shrink(self, rows: List[dict], excess: int,
                     now: float) -> List[dict]:
        """Retire = drain-then-kill: re-point the victim's children at
        its upstream NOW, kill only on a later round's observed-empty
        scrape. Only controller-spawned relays are candidates — the
        controller never kills a node an operator started."""
        actions = []
        spawned = self.manifest.spawned("relays")
        candidates = sorted(
            r["listen"] for r in rows
            if r.get("upstream") is not None
            and r["listen"] in spawned
            and r["listen"] not in self._retiring
        )
        for listen in list(reversed(candidates))[:excess]:
            row = next(r for r in rows if r.get("listen") == listen)
            actions.append({
                "verb": "scale", "key": f"scale:retire:{listen}",
                "evidence": row["endpoint"],
                "fn": lambda l=listen, r=rows: self._retire(l, r),
            })
        return actions

    def _grow(self) -> None:
        listen, _ = self._spawn_relay(self.spec.root)
        log.info("scaled up: relay %s under %s", listen, self.spec.root)

    def _retire(self, listen: str, rows: List[dict]) -> None:
        victim = next(r for r in rows if r.get("listen") == listen)
        upstream = victim.get("upstream") or self.spec.root
        children = [r for r in rows if r.get("upstream") == listen]
        for child in children:
            repoint_relay(child["listen"], upstream,
                          secret=self.spec.secret)
        self._retiring.add(listen)
        log.info("retiring relay %s (%d children re-pointed to %s); "
                 "kill follows the observed drain", listen,
                 len(children), upstream)
        flight.note("control.retire", listen=listen,
                    children=len(children))

    def _kill_retired(self, listen: str, spec_str: str) -> None:
        meta = self.manifest.spawned("relays").get(listen) or {}
        self._terminate(listen, meta.get("pid"))
        self.manifest.forget_spawn("relays", listen)
        self._retiring.discard(listen)
        self._endpoints.pop(spec_str, None)
        self._ident.pop(spec_str, None)
        self._down.pop(spec_str, None)
        self._last_ok.pop(spec_str, None)
        log.info("retired relay %s (observed drained)", listen)
        flight.note("control.retired", listen=listen)

    # --- migrate ---

    def _plan_migrate(self, now: float) -> List[dict]:
        if not self.spec.sessions and \
                not self.manifest.pending_migrations():
            return []
        actions = []
        # Crash resume FIRST: an open intent is a migration mid-flight
        # whose legs must be re-driven to done/aborted before any new
        # intent for the same placement diff is considered.
        for rid, rec in sorted(self.manifest.pending_migrations().items()):
            actions.append({
                "verb": "migrate", "key": f"migrate:{rec['sid']}",
                "evidence": self._engine_evidence(rec["src"]),
                "fn": lambda r=rid, m=rec: self._drive_migration(r, m),
            })
        planned = {a["key"] for a in actions}
        locations = self._session_locations()
        for sid, dst in sorted(self.spec.sessions.items()):
            if f"migrate:{sid}" in planned:
                continue
            src = locations.get(sid)
            if dst == "auto":
                dst = self._pick_auto_destination(src)
            if src is None or src == dst or dst is None:
                continue
            actions.append({
                "verb": "migrate", "key": f"migrate:{sid}",
                "evidence": self._engine_evidence(src),
                "fn": lambda s=sid, a=src, b=dst:
                    self._begin_migration(s, a, b),
            })
        return actions

    def _pick_auto_destination(self, src: Optional[str]
                               ) -> Optional[str]:
        """Ledger-driven placement for `sessions[sid] == "auto"`: the
        cheapest-loaded declared engine wins (accounting plane,
        `engine_cost`). Ties break to the CURRENT location first — a
        session never churns between equally-loaded engines — then
        lexicographic addr, so the pick is deterministic for any
        ledger state."""
        if not self.spec.engines:
            return None
        ranked = sorted(
            (engine_cost(e.out), e.addr != src, e.addr)
            for e in self.spec.engines
        )
        return ranked[0][2]

    def _engine_evidence(self, addr: Optional[str]) -> Optional[str]:
        if addr is None:
            return None
        eng = self.spec.engine(addr)
        return eng.metrics if eng is not None else None

    def _session_locations(self) -> Dict[str, str]:
        """sid -> engine addr, from live list() verbs (parked sessions
        included — a parked session still LIVES somewhere)."""
        out: Dict[str, str] = {}
        for eng in self.spec.engines:
            try:
                for s in self._ctl(eng.addr).list():
                    out.setdefault(s["id"], eng.addr)
            except Exception as e:
                log.warning("cannot list sessions on %s: %s",
                            eng.addr, e)
        return out

    def _begin_migration(self, sid: str, src: str, dst: str) -> None:
        rid = self.manifest.migration_begin(sid, src, dst)
        rec = self.manifest.migration(rid)
        self._drive_migration(rid, rec)

    def _drive_migration(self, rid: str, rec: dict) -> None:
        """Drive one migration's legs to convergence. Every leg is
        state-based idempotent on the engine side, so this function is
        safe to re-enter from any point — which is exactly what a
        controller SIGKILL between legs turns into."""
        from gol_tpu.sessions.manager import SessionError

        sid, src, dst = rec["sid"], rec["src"], rec["dst"]
        src_eng, dst_eng = self.spec.engine(src), self.spec.engine(dst)
        if src_eng is None or dst_eng is None:
            self.manifest.migration_abort(
                rid, "src/dst no longer declared in the spec")
            return
        dst_ctl = self._ctl(dst)
        src_ctl = self._ctl(src)
        on_dst = {s["id"] for s in dst_ctl.list()}
        try:
            if sid not in on_dst:
                on_src = {s["id"] for s in src_ctl.list()}
                if sid not in on_src:
                    self.manifest.migration_abort(
                        rid, f"session {sid} observed on neither "
                             f"{src} nor {dst}")
                    return
                src_ctl.park(sid)
                dst_ctl.adopt(sid, os.path.abspath(src_eng.out))
            # Adopt landed (this round or a pre-crash one): the source
            # copy retires. destroy is tombstone-first and idempotent,
            # so a crash between adopt and destroy re-runs it safely.
            src_ctl.destroy(sid)
        except SessionError as e:
            # A durable verb rejection (not a link failure): the
            # migration cannot converge. The session stays PARKED on
            # the source — its next attach rehydrates it there, which
            # is the rollback.
            self.manifest.migration_abort(rid, str(e))
            flight.note("control.migrate_abort", sid=sid,
                        reason=str(e))
            return
        self.manifest.migration_done(rid, serving=dst)
        log.info("migrated session %s: %s -> %s", sid, src, dst)
        tracing.event("control.migrate", "lifecycle", sid=sid,
                      src=src, dst=dst)
        flight.note("control.migrate", sid=sid, src=src, dst=dst)

    def _ctl(self, addr: str):
        ctl = self._ctls.get(addr)
        if ctl is None:
            from gol_tpu.distributed.client import SessionControl

            host, _, port = addr.rpartition(":")
            ctl = SessionControl(
                host, int(port), secret=self.spec.secret,
                timeout=15.0, retry_window=20.0,
                retry_seed=self._rng.randrange(2 ** 31),
            )
            self._ctls[addr] = ctl
        return ctl

    # --- roll ---

    def _plan_roll(self, rows: List[dict], now: float) -> List[dict]:
        gen = self.spec.roll_generation
        state = self.manifest.roll_state()
        if gen <= 0 or (state["generation"] == gen
                        and not self._roll_pending(state)):
            return []
        self.manifest.roll_start(gen)
        done = set(self.manifest.roll_done())
        # One engine per round — the whole point of a ROLLING restart.
        for eng in self.spec.engines:
            if not eng.spawn or eng.addr in done:
                continue
            return [{
                "verb": "roll", "key": f"roll:{gen}:{eng.addr}",
                "evidence": eng.metrics,
                "fn": lambda e=eng, g=gen: self._roll_engine(e, g),
            }]
        return []

    def _roll_pending(self, state: dict) -> bool:
        done = set(state.get("done", []))
        return any(e.spawn and e.addr not in done
                   for e in self.spec.engines)

    def _roll_engine(self, eng: EngineSpec, gen: int) -> None:
        """drain -> SIGTERM -> respawn with --resume latest -> mark.
        Drain checkpoints every resident session and refuses new
        session attaches, so the restart window loses nothing; the
        respawned engine rehydrates behind coalesced BoardSync."""
        # A fresh control connection, evicted from the cache — after
        # the restart the cached link would point at a dead socket.
        ctl = self._ctl(eng.addr)
        self._ctls.pop(eng.addr, None)
        try:
            ctl.drain()
        finally:
            with contextlib.suppress(Exception):
                ctl.close()
        meta = self.manifest.spawned("engines").get(eng.addr) or {}
        self._terminate(eng.addr, meta.get("pid"))
        self._spawn_engine(eng)
        self.manifest.roll_mark(eng.addr)
        log.info("rolled engine %s (generation %d)", eng.addr, gen)
        tracing.event("control.roll", "lifecycle", addr=eng.addr,
                      generation=gen)
        flight.note("control.roll", addr=eng.addr, generation=gen)

    # --- process spawning (the chaos-harness banner idiom) ---

    def _spawn_relay(self, upstream: str) -> Tuple[str, str]:
        cmd = [sys.executable, "-m", "gol_tpu",
               "--relay", upstream, "--serve", "127.0.0.1:0",
               "--metrics-port", "0"] + list(self.spec.spawn_args)
        if self.spec.secret is not None:
            cmd += ["--secret", self.spec.secret]
        listen, metrics = self._spawn(cmd, "relay", _RELAY_BANNER)
        self._endpoints.setdefault(metrics, Endpoint(metrics))
        self.manifest.record_spawn("relays", listen, metrics,
                                   self._procs[listen].pid)
        return listen, metrics

    def _spawn_engine(self, eng: EngineSpec) -> Tuple[str, str]:
        host, _, port = eng.addr.rpartition(":")
        cmd = [sys.executable, "-m", "gol_tpu", "-noVis",
               "--serve", eng.addr, "--sessions",
               "--out", os.path.abspath(eng.out),
               "--metrics-port",
               eng.metrics.rpartition(":")[2] if eng.metrics else "0",
               "--resume", "latest"] + list(eng.args)
        if self.spec.secret is not None:
            cmd += ["--secret", self.spec.secret]
        listen, metrics = self._spawn(cmd, f"engine-{port}",
                                      _ENGINE_BANNER, key=eng.addr)
        self._endpoints.setdefault(metrics, Endpoint(metrics))
        self.manifest.record_spawn("engines", eng.addr, metrics,
                                   self._procs[eng.addr].pid)
        return eng.addr, metrics

    def _spawn(self, cmd: List[str], tag: str, banner: "re.Pattern",
               key: Optional[str] = None,
               boot_timeout: float = 60.0) -> Tuple[str, str]:
        """Start one fleet process, wait for its serving + metrics
        banners (the chaos harness's log-parse idiom — the child binds
        port 0 and the banner is the only place the real port
        exists)."""
        logs = os.path.join(self.out_dir, "logs")
        os.makedirs(logs, exist_ok=True)
        log_path = os.path.join(
            logs, f"{tag}-{int(time.time() * 1000)}.log")
        env = dict(os.environ)
        repo = os.path.dirname(os.path.dirname(os.path.abspath(
            sys.modules["gol_tpu"].__file__)))
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        with open(log_path, "w") as lf:
            proc = subprocess.Popen(cmd, stdout=lf,
                                    stderr=subprocess.STDOUT, env=env)
        deadline = time.monotonic() + boot_timeout
        listen = metrics = None
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"spawned {tag} died during boot — see {log_path}")
            with open(log_path) as f:
                for line in f:
                    m = banner.search(line)
                    if m:
                        listen = m.group(1)
                    m = _METRICS_BANNER.search(line)
                    if m:
                        metrics = m.group(1)
            if listen and metrics:
                self._procs[key or listen] = proc
                return listen, metrics
            if self._shutdown.wait(0.1):
                break
        with contextlib.suppress(Exception):
            proc.kill()
        raise RuntimeError(
            f"spawned {tag} never printed its banners — see {log_path}")

    def _terminate(self, key: str, pid: Optional[int]) -> None:
        """SIGTERM + reap a node we own: the in-process Popen handle
        when we have one, the manifest pid after a controller restart
        (the child survived OUR death, not its own)."""
        proc = self._procs.pop(key, None)
        if proc is not None:
            with contextlib.suppress(OSError):
                proc.terminate()
            with contextlib.suppress(Exception):
                proc.wait(timeout=15)
            return
        if pid:
            with contextlib.suppress(OSError):
                os.kill(pid, signal.SIGTERM)
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline and _pid_alive(pid):
                time.sleep(0.1)


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except OSError:
        return True
    return True
