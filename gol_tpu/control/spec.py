"""Desired-state spec for the fleet controller (docs/CONTROL.md).

One JSON file declares what the fleet SHOULD look like; the
controller's reconcile loop makes observed state match it. The format
follows the alert-rules file's discipline (obs.freshness.load_rules):
plain JSON, strict validation at load time, every mistake a
SpecError naming the offending field — a controller that boots on a
typo'd spec and reconciles toward garbage is worse than one that
refuses to start.

Minimal spec (heal-only, no engines):

    {
      "root": "127.0.0.1:8100",
      "scrape": ["9100", "9101", "9102"],
      "relays": {"min": 2}
    }

Full shape:

    {
      "root": "HOST:PORT",            # upstream for spawned relays
      "scrape": ["HOST:PORT", ...],   # static /metrics sidecars
      "secret": "TOKEN" | null,
      "relays": {
        "min": 0, "max": 8,           # relay-count bounds
        "observers_per_relay": 64     # grow/shrink load threshold
      },
      "engines": [
        {"addr": "HOST:PORT", "out": "outA",
         "metrics": "HOST:PORT" | null,
         "spawn": false, "args": ["--platform", "cpu", ...]}
      ],
      "sessions": {"SID": "ENGINE-ADDR" | "auto", ...},  # placement
                                      # ("auto": cheapest engine by
                                      #  the accounting-plane ledger)
      "collector": "HOST:PORT" | null,  # history-plane collector:
      "canary_max_age_s": 2.0,        #  scale on the canary's
      "canary_for_secs": 10.0,        #  SUSTAINED measured turn age
      "roll_generation": 0,           # bump to roll managed engines
      "interval_secs": 2.0,           # reconcile cadence
      "stale_secs": 15.0,             # refuse to act on older scrapes
      "down_rounds": 2,               # consecutive misses = dead
      "actions_per_round": 2,         # the spawn-storm budget
      "heal_alerts": ["rule", ...],   # firing = relay needs healing
      "spawn_args": ["--platform", "cpu"]   # extra argv for relays
    }
"""

from __future__ import annotations

import json
import os
import re
from typing import List, Optional

__all__ = ["EngineSpec", "FleetSpec", "SpecError", "load_spec"]

_ADDR = re.compile(r"^[A-Za-z0-9_.-]+:\d{1,5}$")


class SpecError(ValueError):
    """A malformed controller spec; the message names the field."""


def _addr(value, field: str) -> str:
    if not isinstance(value, str) or not _ADDR.match(value):
        raise SpecError(f"{field}: expected HOST:PORT, got {value!r}")
    return value


def _num(value, field: str, lo: float, default: float) -> float:
    if value is None:
        return default
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        raise SpecError(f"{field}: expected a number, got {value!r}")
    if value < lo:
        raise SpecError(f"{field}: must be >= {lo}, got {value!r}")
    return float(value)


class EngineSpec:
    """One session engine the controller observes (and, with
    `spawn: true`, owns: spawned at boot, drained + restarted with
    `--resume latest` on a roll)."""

    def __init__(self, raw: dict, index: int):
        field = f"engines[{index}]"
        if not isinstance(raw, dict):
            raise SpecError(f"{field}: expected an object")
        self.addr = _addr(raw.get("addr"), f"{field}.addr")
        out = raw.get("out")
        if not isinstance(out, str) or not out:
            raise SpecError(f"{field}.out: expected a directory path")
        self.out = out
        self.metrics: Optional[str] = None
        if raw.get("metrics") is not None:
            self.metrics = _addr(raw["metrics"], f"{field}.metrics")
        self.spawn = bool(raw.get("spawn", False))
        args = raw.get("args", [])
        if not (isinstance(args, list)
                and all(isinstance(a, str) for a in args)):
            raise SpecError(f"{field}.args: expected a list of strings")
        self.args: List[str] = list(args)


class FleetSpec:
    """The parsed, validated desired state. Attribute-bag by design:
    the controller reads it, never mutates it — a reconcile loop with
    a drifting spec has no level to trigger on."""

    def __init__(self, raw: dict, path: str = "<inline>"):
        if not isinstance(raw, dict):
            raise SpecError("spec: expected a JSON object")
        self.path = path
        self.root = _addr(raw.get("root"), "root")
        scrape = raw.get("scrape", [])
        if not (isinstance(scrape, list)
                and all(isinstance(s, str) and s for s in scrape)):
            raise SpecError("scrape: expected a list of endpoint specs")
        self.scrape: List[str] = list(scrape)
        secret = raw.get("secret")
        if secret is not None and not isinstance(secret, str):
            raise SpecError("secret: expected a string or null")
        self.secret: Optional[str] = secret

        relays = raw.get("relays", {})
        if not isinstance(relays, dict):
            raise SpecError("relays: expected an object")
        self.relay_min = int(_num(relays.get("min"), "relays.min", 0, 0))
        self.relay_max = int(_num(relays.get("max"), "relays.max", 0, 8))
        if self.relay_max < self.relay_min:
            raise SpecError("relays.max: must be >= relays.min")
        self.observers_per_relay = _num(
            relays.get("observers_per_relay"),
            "relays.observers_per_relay", 1, 64,
        )

        raw_engines = raw.get("engines", [])
        if not isinstance(raw_engines, list):
            raise SpecError("engines: expected a list")
        self.engines = [EngineSpec(e, i)
                        for i, e in enumerate(raw_engines)]
        by_addr = {e.addr: e for e in self.engines}
        if len(by_addr) != len(self.engines):
            raise SpecError("engines: duplicate addr")

        sessions = raw.get("sessions", {})
        if not isinstance(sessions, dict):
            raise SpecError("sessions: expected an object (sid -> addr)")
        for sid, addr in sessions.items():
            if not isinstance(sid, str) or not sid:
                raise SpecError(f"sessions: bad session id {sid!r}")
            if addr == "auto":
                # Ledger-driven placement: the controller picks the
                # cheapest-loaded declared engine (accounting plane,
                # deterministic tie-break) at reconcile time.
                if not self.engines:
                    raise SpecError(
                        f"sessions[{sid!r}]: \"auto\" placement needs "
                        "at least one declared engine"
                    )
                continue
            _addr(addr, f"sessions[{sid!r}]")
            if addr not in by_addr:
                raise SpecError(
                    f"sessions[{sid!r}]: {addr!r} is not a declared "
                    "engine addr"
                )
        self.sessions = dict(sessions)

        self.roll_generation = int(_num(
            raw.get("roll_generation"), "roll_generation", 0, 0))
        self.interval_secs = _num(
            raw.get("interval_secs"), "interval_secs", 0.05, 2.0)
        self.stale_secs = _num(
            raw.get("stale_secs"), "stale_secs", 0.1, 15.0)
        self.down_rounds = int(_num(
            raw.get("down_rounds"), "down_rounds", 1, 2))
        self.actions_per_round = int(_num(
            raw.get("actions_per_round"), "actions_per_round", 1, 2))
        # History plane (docs/OBSERVABILITY.md): with a collector
        # declared, the scale rule reads the canary's MEASURED turn-age
        # history from it — sustained breach over canary_for_secs
        # grows the tree, sustained deep comfort shrinks it; no
        # collector (or a failed query) falls back to raw peer counts.
        collector = raw.get("collector")
        if collector is not None:
            collector = _addr(collector, "collector")
        self.collector: Optional[str] = collector
        max_age = raw.get("canary_max_age_s")
        self.canary_max_age_s: Optional[float] = None \
            if max_age is None \
            else _num(max_age, "canary_max_age_s", 0.001, 0.0)
        self.canary_for_secs = _num(
            raw.get("canary_for_secs"), "canary_for_secs", 0.5, 10.0)
        if self.canary_max_age_s is not None and collector is None:
            raise SpecError(
                "canary_max_age_s: needs a collector (the history "
                "scale rule reads canary age from it)"
            )
        alerts = raw.get("heal_alerts", [])
        if not (isinstance(alerts, list)
                and all(isinstance(a, str) for a in alerts)):
            raise SpecError("heal_alerts: expected a list of rule names")
        self.heal_alerts: List[str] = list(alerts)
        spawn_args = raw.get("spawn_args", [])
        if not (isinstance(spawn_args, list)
                and all(isinstance(a, str) for a in spawn_args)):
            raise SpecError("spawn_args: expected a list of strings")
        self.spawn_args: List[str] = list(spawn_args)

    def engine(self, addr: str) -> Optional[EngineSpec]:
        for e in self.engines:
            if e.addr == addr:
                return e
        return None


def load_spec(path: "str | os.PathLike") -> FleetSpec:
    """Parse + validate a spec file; raises SpecError on anything
    malformed (including unreadable files — the CLI turns that into a
    startup SystemExit, exactly like --alert-rules)."""
    try:
        with open(path) as f:
            raw = json.load(f)
    except OSError as e:
        raise SpecError(f"cannot read spec: {e}") from None
    except ValueError as e:
        raise SpecError(f"spec is not valid JSON: {e}") from None
    return FleetSpec(raw, path=os.fspath(path))
