"""Control plane — the reconciling fleet controller (docs/CONTROL.md).

PR 15 turned staleness into SLOs and alerts, PR 16 enforced the lock
contracts, PR 17 attributed every device-second — but nothing ACTED
when a relay died or an engine saturated (ROADMAP item 6). This
package closes the loop with the same stdlib-sidecar idiom the obs
planes use: a controller process (`python -m gol_tpu --control
SPEC.json`) owns fleet topology as a declarative desired-state spec
and runs a level-triggered reconcile loop over observed state — the
`gol_tpu.obs.scrape` fleet join it shares with the console.

Verbs (docs/CONTROL.md "Reconcile rules"):

- **heal** — a dead or turn-age-alerting relay is replaced by a fresh
  `--relay` spawn; its orphaned downstream subtree is re-pointed
  (`RelayNode.repoint`) at the replacement. Leaf clients ride the
  PR 3 reconnect/backoff + BoardSync resume, so healing is bit-exact
  by construction.
- **scale** — observer-count thresholds grow/shrink the relay tree;
  retire is drain-then-kill (children re-pointed first, the retiree
  killed only once its peer count is OBSERVED at zero), never
  kill-then-hope.
- **migrate** — park on engine A, adopt on engine B, destroy the
  parked record on A, flip the serving endpoint: a two-phase record
  in the crash-atomic controller manifest makes a controller SIGKILL
  mid-migration resume or abort, never duplicate (every leg verb is
  idempotent under retry, state-based).
- **roll** — drain/restart managed engines one at a time behind
  coalesced BoardSync, `--resume latest` covering the gap.

Every action is seeded-jitter backed-off, budget-capped per reconcile
round, and refused outright when the observed state backing it is
stale (`FleetSpec.stale_secs`).
"""

from gol_tpu.control.spec import FleetSpec, SpecError, load_spec
from gol_tpu.control.manifest import ControllerManifest
from gol_tpu.control.controller import Controller, repoint_relay

__all__ = [
    "Controller",
    "ControllerManifest",
    "FleetSpec",
    "SpecError",
    "load_spec",
    "repoint_relay",
]
