"""Visualiser layer: native/headless pixel boards + the event loop."""

from gol_tpu.visual.board import NativeBoard, NumpyBoard, make_board
from gol_tpu.visual.loop import run_loop

__all__ = ["NativeBoard", "NumpyBoard", "make_board", "run_loop"]
