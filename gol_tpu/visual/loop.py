"""Visualiser event loop — the analog of the reference's SDL loop
(ref: sdl/loop.go:9-54).

Consumes the engine's event stream and drives a pixel board:
`CellFlipped` flips a pixel, `TurnComplete` presents a frame,
`FinalTurnComplete` (or stream close) tears the window down; any other
event with a non-empty string form is printed as
`Completed Turns N <event>` (ref: sdl/loop.go:36-47). Window keyboard
events for p/s/q/k are forwarded into the engine's keypress queue
(ref: sdl/loop.go:18-27).

The board is windowed when the native core finds libSDL2 at runtime and
headless (shadow framebuffer) otherwise — headless-with-a-shadow-board
is exactly the protocol harness of the reference's `-noVis` tests
(ref: sdl_test.go:18-90), so the same loop serves interactive use and
protocol testing.
"""

from __future__ import annotations

import queue
from typing import Callable, Optional

from gol_tpu.events import (
    CellFlipped,
    FinalTurnComplete,
    FlipBatch,
    TurnComplete,
)
from gol_tpu.params import Params
from gol_tpu.visual.board import make_board

_KEYS = ("p", "s", "q", "k")


def run_loop(
    params: Params,
    events,
    keypresses: Optional[queue.Queue] = None,
    board=None,
    want_window: bool = True,
    on_turn: Optional[Callable[[int, int], None]] = None,
    printer: Callable[[str], None] = print,
    levels: bool = False,
):
    """Drive `board` from `events` until the run ends; returns the board
    (not yet destroyed when the caller supplied it, for assertions).

    `on_turn(completed_turns, board_count)` fires after each rendered
    turn — the hook the protocol tests use to compare the shadow board
    against expected alive counts (ref: sdl_test.go:62-74,110-116).

    `levels=True` builds a gray-level board (multi-state Generations
    rules, r5): FlipBatch events carrying per-cell levels SET those
    cells; the board's count() is the ALIVE (level-255) count."""
    own_board = board is None
    if own_board:
        board = make_board(params.image_width, params.image_height,
                           want_window, levels=levels)

    try:
        while True:
            # Forward pending window keys (ref: sdl/loop.go:14-28).
            while True:
                key = board.poll_key()
                if key is None:
                    break
                if key == "CLOSE":
                    if keypresses is not None:
                        keypresses.put("q")
                elif key in _KEYS and keypresses is not None:
                    keypresses.put(key)

            # Block briefly so key polling stays live even when the
            # engine is quiet (the Go loop busy-polls instead).
            try:
                ev = events.get(timeout=0.05)
            except queue.Empty:
                continue
            if ev is None:  # stream closed (ref: sdl/loop.go:31-34)
                return board

            if isinstance(ev, CellFlipped):
                board.flip(ev.cell.x, ev.cell.y)
            elif isinstance(ev, FlipBatch):
                if getattr(ev, "levels", None) is not None:
                    # Multi-state batch: SET each cell's gray level.
                    board.update_levels(ev.cells, ev.levels)
                else:
                    # One vectorized XOR per turn (the opt-in batch
                    # form — semantically N CellFlipped events).
                    board.flip_batch(ev.cells)
            elif isinstance(ev, TurnComplete):
                board.render()
                if on_turn is not None:
                    on_turn(ev.completed_turns, board.count())
            elif isinstance(ev, FinalTurnComplete):
                return board
            else:
                s = str(ev)
                if s:
                    # (ref: sdl/loop.go:44-47 format)
                    printer(f"Completed Turns {ev.completed_turns:<8}{s}")
    finally:
        if own_board:
            board.destroy()
