"""Pixel-board bindings — ctypes over the native C++ core, with a pure
NumPy shadow board as fallback.

The native core (`gol_tpu/native/board.cpp`) is the analog of the
reference's SDL window wrapper (ref: sdl/window.go); when libSDL2 is
present at runtime it opens a real window, otherwise it is a headless
framebuffer — the stand-in the reference's tests build by hand
(ref: sdl_test.go:18-90, the `-noVis` shadow board).
"""

from __future__ import annotations

import ctypes
import os
import pathlib
import subprocess
import threading

import numpy as np

def _batch_mask(cells, width: int, height: int) -> "np.ndarray | None":
    """(N, 2) x,y pairs -> a {0,1} (H, W) flip mask, or None for an
    empty batch; bounds-checked with the same strictness as per-pixel
    flips. (A FlipBatch never contains duplicates — it comes from a
    mask — so one mask XOR equals N pixel flips.)"""
    cells = np.asarray(cells, dtype=np.int64).reshape(-1, 2)
    if len(cells) == 0:
        return None
    xs, ys = cells[:, 0], cells[:, 1]
    if (xs.min() < 0 or ys.min() < 0
            or int(xs.max()) >= width or int(ys.max()) >= height):
        raise IndexError("pixel out of range")
    mask = np.zeros((height, width), np.uint8)
    mask[ys, xs] = 1
    return mask


_NATIVE_DIR = pathlib.Path(__file__).resolve().parent.parent / "native"
_LIB_PATH = _NATIVE_DIR / "libgolvis.so"
_build_lock = threading.Lock()


def _load_native() -> ctypes.CDLL | None:
    """Build (once, cached as a .so next to the source) and load the
    native core; None when no toolchain is available."""
    with _build_lock:
        src = _NATIVE_DIR / "board.cpp"
        try:
            if not _LIB_PATH.exists() or _LIB_PATH.stat().st_mtime < src.stat().st_mtime:
                subprocess.run(
                    ["make", "-C", str(_NATIVE_DIR), "libgolvis.so"],
                    check=True,
                    capture_output=True,
                )
            lib = ctypes.CDLL(str(_LIB_PATH))
        except (OSError, subprocess.CalledProcessError):
            return None
    lib.golvis_create.restype = ctypes.c_void_p
    lib.golvis_create.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_int]
    for fn, res, args in [
        ("golvis_has_window", ctypes.c_int, [ctypes.c_void_p]),
        ("golvis_flip_pixel", ctypes.c_int, [ctypes.c_void_p, ctypes.c_int, ctypes.c_int]),
        ("golvis_set_pixel", ctypes.c_int,
         [ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int]),
        ("golvis_get_pixel", ctypes.c_int, [ctypes.c_void_p, ctypes.c_int, ctypes.c_int]),
        ("golvis_count_pixels", ctypes.c_long, [ctypes.c_void_p]),
        ("golvis_clear", None, [ctypes.c_void_p]),
        ("golvis_load_mask", None, [ctypes.c_void_p, ctypes.c_char_p]),
        ("golvis_flip_mask", None, [ctypes.c_void_p, ctypes.c_char_p]),
        # Gray-level mode (multi-state rules, r5).
        ("golvis_load_levels", None, [ctypes.c_void_p, ctypes.c_char_p]),
        ("golvis_update_levels", None,
         [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p]),
        ("golvis_set_level", ctypes.c_int,
         [ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int]),
        ("golvis_get_level", ctypes.c_int,
         [ctypes.c_void_p, ctypes.c_int, ctypes.c_int]),
        ("golvis_count_level", ctypes.c_long, [ctypes.c_void_p, ctypes.c_int]),
        ("golvis_toggle_mask", None, [ctypes.c_void_p, ctypes.c_char_p]),
        ("golvis_render", None, [ctypes.c_void_p]),
        ("golvis_poll_key", ctypes.c_int, [ctypes.c_void_p]),
        ("golvis_destroy", None, [ctypes.c_void_p]),
    ]:
        f = getattr(lib, fn)
        f.restype = res
        f.argtypes = args
    return lib


_native: ctypes.CDLL | None = None
_native_tried = False


def native_lib() -> ctypes.CDLL | None:
    global _native, _native_tried
    if not _native_tried:
        _native = _load_native()
        _native_tried = True
    return _native


class NativeBoard:
    """ctypes handle over the C++ board (windowed or headless)."""

    def __init__(self, width: int, height: int, want_window: bool = False):
        lib = native_lib()
        if lib is None:
            raise RuntimeError("native visualiser core unavailable")
        self._lib = lib
        self.width, self.height = width, height
        self._h = lib.golvis_create(width, height, 1 if want_window else 0)
        if not self._h:
            raise RuntimeError("golvis_create failed")

    @property
    def has_window(self) -> bool:
        return bool(self._lib.golvis_has_window(self._h))

    def _check(self, rc: int) -> None:
        if rc < 0:
            # The reference panics on out-of-range flips (ref: sdl/window.go:80-82).
            raise IndexError("pixel out of range")

    def flip(self, x: int, y: int) -> None:
        self._check(self._lib.golvis_flip_pixel(self._h, x, y))

    def set(self, x: int, y: int, on: bool) -> None:
        self._check(self._lib.golvis_set_pixel(self._h, x, y, 1 if on else 0))

    def get(self, x: int, y: int) -> bool:
        rc = self._lib.golvis_get_pixel(self._h, x, y)
        self._check(rc)
        return bool(rc)

    def count(self) -> int:
        return self._lib.golvis_count_pixels(self._h)

    def clear(self) -> None:
        self._lib.golvis_clear(self._h)

    def load_mask(self, mask: np.ndarray) -> None:
        self._lib.golvis_load_mask(self._h, self._as_bytes(mask))

    def flip_mask(self, mask: np.ndarray) -> None:
        self._lib.golvis_flip_mask(self._h, self._as_bytes(mask))

    def flip_batch(self, cells) -> None:
        """XOR a whole turn's (x, y) flips in one native call
        (events.FlipBatch payloads)."""
        mask = _batch_mask(cells, self.width, self.height)
        if mask is not None:
            self.flip_mask(mask)

    def _as_bytes(self, mask: np.ndarray) -> bytes:
        m = np.ascontiguousarray(mask, dtype=np.uint8)
        if m.shape != (self.height, self.width):
            raise ValueError(f"mask shape {m.shape} != {(self.height, self.width)}")
        return m.tobytes()

    def render(self) -> None:
        self._lib.golvis_render(self._h)

    def poll_key(self) -> str | None:
        """Next pending key as a one-char string, 'CLOSE' on window close,
        None when no events are pending (headless boards never have any)."""
        k = self._lib.golvis_poll_key(self._h)
        if k == -1:
            return "CLOSE"
        if k > 0 and 32 <= k < 127:
            return chr(k)
        return None

    def destroy(self) -> None:
        if self._h:
            self._lib.golvis_destroy(self._h)
            self._h = None


class NumpyBoard:
    """Pure-python shadow board — same surface, zero dependencies."""

    has_window = False

    def __init__(self, width: int, height: int, want_window: bool = False):
        self.width, self.height = width, height
        self._px = np.zeros((height, width), dtype=bool)

    def _check(self, x: int, y: int) -> None:
        if not (0 <= x < self.width and 0 <= y < self.height):
            raise IndexError("pixel out of range")

    def flip(self, x: int, y: int) -> None:
        self._check(x, y)
        self._px[y, x] ^= True

    def set(self, x: int, y: int, on: bool) -> None:
        self._check(x, y)
        self._px[y, x] = on

    def get(self, x: int, y: int) -> bool:
        self._check(x, y)
        return bool(self._px[y, x])

    def count(self) -> int:
        return int(self._px.sum())

    def clear(self) -> None:
        self._px[:] = False

    def load_mask(self, mask: np.ndarray) -> None:
        self._px[:] = self._checked(mask)

    def flip_mask(self, mask: np.ndarray) -> None:
        self._px ^= self._checked(mask)

    def flip_batch(self, cells) -> None:
        """XOR a whole turn's (x, y) flips vectorized
        (events.FlipBatch payloads)."""
        mask = _batch_mask(cells, self.width, self.height)
        if mask is not None:
            self.flip_mask(mask)

    def _checked(self, mask: np.ndarray) -> np.ndarray:
        # Same strictness as NativeBoard._as_bytes — no silent broadcast.
        m = np.asarray(mask)
        if m.shape != (self.height, self.width):
            raise ValueError(f"mask shape {m.shape} != {(self.height, self.width)}")
        return m != 0

    def render(self) -> None:
        pass

    def poll_key(self) -> str | None:
        return None

    def destroy(self) -> None:
        pass


def _level_batch(cells, levels, width: int, height: int):
    """(N, 2) x,y pairs + (N,) gray levels -> (mask, grid) full-board
    byte arrays for the bulk native call, bounds-checked like
    `_batch_mask`; (None, None) for an empty batch."""
    cells = np.asarray(cells, dtype=np.int64).reshape(-1, 2)
    levels = np.asarray(levels, dtype=np.uint8).reshape(-1)
    if len(cells) != len(levels):
        raise ValueError(f"{len(cells)} cells vs {len(levels)} levels")
    if len(cells) == 0:
        return None, None
    xs, ys = cells[:, 0], cells[:, 1]
    if (xs.min() < 0 or ys.min() < 0
            or int(xs.max()) >= width or int(ys.max()) >= height):
        raise IndexError("pixel out of range")
    mask = np.zeros((height, width), np.uint8)
    grid = np.zeros((height, width), np.uint8)
    mask[ys, xs] = 1
    grid[ys, xs] = levels
    return mask, grid


class NativeLevelBoard(NativeBoard):
    """Gray-level mode over the same native core (multi-state rules):
    levels SET cells, `count()` is the ALIVE (level 255) count, and
    `count_level` gives the per-level histogram the protocol tests
    assert on. Two-state events (flip/flip_batch) toggle dead<->alive
    at the LEVEL semantics — never the raw ARGB XOR, which would turn
    grays into invalid encodings — so both level-board variants agree
    on mixed streams."""

    def flip(self, x: int, y: int) -> None:
        self.set_level(x, y, 0 if self.get_level(x, y) else 255)

    def flip_mask(self, mask: np.ndarray) -> None:
        self._lib.golvis_toggle_mask(self._h, self._as_bytes(mask))

    def load_levels(self, grid: np.ndarray) -> None:
        self._lib.golvis_load_levels(self._h, self._as_bytes(grid))

    def update_levels(self, cells, levels) -> None:
        mask, grid = _level_batch(cells, levels, self.width, self.height)
        if mask is not None:
            self._lib.golvis_update_levels(
                self._h, mask.tobytes(), grid.tobytes()
            )

    def set_level(self, x: int, y: int, level: int) -> None:
        self._check(self._lib.golvis_set_level(self._h, x, y, int(level)))

    def get_level(self, x: int, y: int) -> int:
        rc = self._lib.golvis_get_level(self._h, x, y)
        self._check(rc)
        return rc

    def count(self) -> int:
        return self.count_level(255)

    def count_level(self, level: int) -> int:
        n = self._lib.golvis_count_level(self._h, int(level))
        if n < 0:
            raise ValueError(f"bad level {level}")
        return n


class NumpyLevelBoard:
    """Pure-python gray-level shadow board — the NumpyBoard analog for
    multi-state rules. Storage is the uint8 level grid itself.
    Two-state events toggle dead<->alive at level semantics, matching
    NativeLevelBoard on mixed streams."""

    has_window = False

    def __init__(self, width: int, height: int, want_window: bool = False):
        self.width, self.height = width, height
        self._px = np.zeros((height, width), dtype=np.uint8)

    def flip(self, x: int, y: int) -> None:
        self.set_level(x, y, 0 if self.get_level(x, y) else 255)

    def set(self, x: int, y: int, on: bool) -> None:
        self.set_level(x, y, 255 if on else 0)

    def get(self, x: int, y: int) -> bool:
        return self.get_level(x, y) != 0

    def _checked(self, grid) -> np.ndarray:
        g = np.asarray(grid, np.uint8)
        if g.shape != (self.height, self.width):
            raise ValueError(
                f"grid shape {g.shape} != {(self.height, self.width)}"
            )
        return g

    def load_levels(self, grid) -> None:
        self._px[:] = self._checked(grid)

    def update_levels(self, cells, levels) -> None:
        mask, grid = _level_batch(cells, levels, self.width, self.height)
        if mask is not None:
            self._px = np.where(mask != 0, grid, self._px)

    def flip_batch(self, cells) -> None:
        # Two-state batches still arrive (e.g. a Life peer's board-sync
        # replay): toggle between dead and full-level alive.
        mask = _batch_mask(cells, self.width, self.height)
        if mask is not None:
            self.flip_mask(mask)

    def flip_mask(self, mask: np.ndarray) -> None:
        m = np.asarray(mask)
        if m.shape != (self.height, self.width):
            raise ValueError(
                f"mask shape {m.shape} != {(self.height, self.width)}"
            )
        self._px = np.where(
            m != 0,
            np.where(self._px != 0, 0, 255).astype(np.uint8),
            self._px,
        )

    def set_level(self, x: int, y: int, level: int) -> None:
        if not (0 <= x < self.width and 0 <= y < self.height):
            raise IndexError("pixel out of range")
        if not (0 <= int(level) <= 255):
            # Same error contract as NativeLevelBoard, whose C core
            # returns -1 for an out-of-range level exactly as for an
            # out-of-range pixel — without this the variants diverge
            # (numpy raises OverflowError, or silently wraps on older
            # releases).
            raise IndexError(f"level {level} out of range 0..255")
        self._px[y, x] = level

    def get_level(self, x: int, y: int) -> int:
        if not (0 <= x < self.width and 0 <= y < self.height):
            raise IndexError("pixel out of range")
        return int(self._px[y, x])

    def count(self) -> int:
        return self.count_level(255)  # alive cells, not dying grays

    def count_level(self, level: int) -> int:
        return int((self._px == np.uint8(level)).sum())

    def clear(self) -> None:
        self._px[:] = 0

    def render(self) -> None:
        pass

    def poll_key(self) -> "str | None":
        return None

    def destroy(self) -> None:
        pass


def make_board(width: int, height: int, want_window: bool = False,
               levels: bool = False):
    """Best available board: native (windowed if SDL2 + display exist),
    NumPy shadow board otherwise. `GOL_TPU_NO_NATIVE=1` forces the
    fallback (for tests). `levels=True` builds the gray-level variant
    (multi-state Generations rules, r5)."""
    if os.environ.get("GOL_TPU_NO_NATIVE") != "1":
        try:
            if levels:
                return NativeLevelBoard(width, height, want_window)
            return NativeBoard(width, height, want_window)
        except RuntimeError:
            pass
    if levels:
        return NumpyLevelBoard(width, height, want_window)
    return NumpyBoard(width, height, want_window)
