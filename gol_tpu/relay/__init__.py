"""gol_tpu.relay — the broadcast tier (docs/RELAY.md).

Three legs turn the one-server serving plane into a fan-out tree:

- `writerpool`: a selectors-based writer event loop — thousands of
  non-blocking peer sockets per pool thread with bounded per-peer byte
  queues, replacing the thread-per-connection writers in both
  `distributed.server` servers (the PR 7 degradation machinery
  operates on the pool's queues unchanged);
- `node`: a store-and-forward relay (`--relay upstream:port`) that
  attaches upstream as ONE batching binary client and re-serves N
  downstream observers by forwarding identical FBATCH/BoardSync bytes
  with zero re-encode — reconnect and clock sync compose per hop;
- `ws`: a stdlib RFC-6455 WebSocket edge gateway riding the same
  relay abstraction — browser observers get the identical binary
  frames inside WS binary messages.
"""

from gol_tpu.relay.writerpool import PoolFull, WriterPool


def __getattr__(name):
    # RelayNode pulls in the whole serving plane (distributed.server);
    # importing it lazily keeps `from gol_tpu.relay import WriterPool`
    # light for the servers themselves (no import cycle).
    if name == "RelayNode":
        from gol_tpu.relay.node import RelayNode

        return RelayNode
    raise AttributeError(name)


__all__ = ["PoolFull", "RelayNode", "WriterPool"]
