"""Selectors-based writer event loop — thousands of sockets per thread.

The thread-per-connection writer the servers shipped with (one daemon
thread + one `queue.Queue` per attached peer) is the wrong shape for a
broadcast tier: at relay-scale peer counts the per-thread stacks alone
dwarf the payloads, and the scheduler burns CPU context-switching
writers that are each asleep 99% of the time. This module is the
replacement: a `WriterPool` owns a few event-loop threads, each running
a `selectors` loop over every socket assigned to it — a peer costs one
registry entry and a bounded byte queue, not a thread.

Contract (what `distributed.server._Conn` builds on):

- `register(sock, on_error)` -> `PoolHandle`; the pool sends on a
  NON-BLOCKING duplicate of the socket's fd, so the caller's reader
  thread keeps its own read deadline on the original socket object
  untouched (CPython socket timeouts are object-level emulation over
  an fd that is already O_NONBLOCK whenever a timeout is set).
- `PoolHandle.enqueue(framed)` queues one fully-framed wire payload;
  bounded in FRAMES (the unit the PR 7 degradation thresholds —
  high-water / LOW_WATER / drain deadline — are expressed in) and in
  BYTES (the new hard cap a byte-queue needs: 1024 tiny heartbeats
  are not 1024 board rasters). Overflow raises `PoolFull` without
  ever blocking the caller — exactly the old queue.Full contract.
- `enqueue(front=True)` jumps the backlog (the clock-probe echo: its
  whole value is a prompt turnaround) while still riding the same
  socket serialization — frames never interleave.
- A peer's socket error fires `on_error(handle)` from the loop thread
  (the old writer-thread death path); a wedged peer never blocks the
  loop — `send()` on the non-blocking duplicate returns EWOULDBLOCK
  and the selector simply stops polling it until writable.
- `request_finish()` + `join()` reproduce the old drain-then-exit
  sentinel: everything already queued is flushed, then `finished`
  sets and the fd leaves the selector.

Fault injection (gol_tpu.testing.faults) is honored per FRAME: when
the registered socket is a `FaultySocket`, the pool consults the
active plan exactly once per frame at first-byte time — the same
"one sendall per frame" accounting the threaded writers had, so
seeded chaos scenarios replay unchanged across the refactor.

Observability: `gol_tpu_writer_pool_busy_seconds_total` accumulates
the wall time loop threads spend actually servicing sends — the
CPU-proxy the relay smoke asserts stays flat as observers double
(encode-once + byte-copy fan-out means added observers cost queue
pushes, not re-encodes).
"""

from __future__ import annotations

import collections
import contextlib
import itertools
import os
import selectors
import socket
import threading
import time
from typing import Callable, Optional

from gol_tpu import obs
from gol_tpu.obs import tracing
from gol_tpu.analysis.concurrency import lockcheck

__all__ = ["PoolFull", "PoolHandle", "WriterPool"]


class PoolFull(Exception):
    """The peer's bounded queue (frames or bytes) is full — the caller
    declares the peer dead, never blocks (the old queue.Full path)."""


class _PoolMetrics:
    def __init__(self):
        self.busy_seconds = obs.counter(
            "gol_tpu_writer_pool_busy_seconds_total",
            "Wall seconds pool threads spent actively servicing sends "
            "(the serving plane's CPU proxy — flat per added observer "
            "under encode-once fan-out)",
        )
        self.frames = obs.counter(
            "gol_tpu_writer_pool_frames_total",
            "Wire frames fully transmitted by pool threads",
        )
        self.sockets = obs.gauge(
            "gol_tpu_writer_pool_sockets",
            "Sockets currently registered across all writer pools",
        )


_METRICS = _PoolMetrics()


class PoolHandle:
    """One registered peer: bounded byte queue + selector membership.
    Queue mutations run under `_lock` (short, never across a send);
    only the owning loop thread consumes."""

    def __init__(self, loop: "_Loop", sock, on_error,
                 max_frames: int, max_bytes: int):
        self._loop = loop
        self._sock = sock  # the caller's socket (fault wrapper included)
        # Non-blocking duplicate for sends: the reader keeps its own
        # timeout semantics on the original object, the pool gets
        # EWOULDBLOCK instead of a 30s emulated block on a full buffer.
        self._wsock = socket.socket(fileno=os.dup(sock.fileno()))
        self._wsock.settimeout(0)
        self._fault = sock if _is_faulty(sock) else None
        self._on_error = on_error
        self.max_frames = max_frames
        self.max_bytes = max_bytes
        self._lock = lockcheck.make_lock("PoolHandle._lock")
        self._q: "collections.deque[bytes]" = collections.deque()
        #: The frame currently transmitting lives OUTSIDE the deque
        #: (popped into this slot by the loop thread): a concurrent
        #: enqueue(front=True) may then appendleft safely — it can
        #: neither interleave into the in-flight frame nor be popped
        #: in its place when that frame completes. Counts include it.
        self._sending: Optional[bytes] = None
        self._send_off = 0
        self._fault_done = False  # plan consulted for `_sending` yet?
        self._frames = 0
        self._bytes = 0
        self._armed = False    # registered for EVENT_WRITE (loop thread)
        self._arming = False   # an arm command is in flight
        self._dead = False
        self._finishing = False
        self.finished = threading.Event()

    # --- caller side ---

    def enqueue(self, payload: bytes, front: bool = False) -> None:
        """Queue one framed payload. Raises BrokenPipeError once the
        peer is dead, PoolFull when either bound is exceeded."""
        need_arm = False
        with self._lock:
            if self._dead:
                raise BrokenPipeError("peer is gone")
            if (self._frames >= self.max_frames
                    or self._bytes + len(payload) > self.max_bytes):
                raise PoolFull(
                    f"{self._frames} frames / {self._bytes} bytes queued"
                )
            if front:
                # Next after whatever is mid-wire (`_sending` is out
                # of the deque) — prompt, never interleaved.
                self._q.appendleft(payload)
            else:
                self._q.append(payload)
            self._frames += 1
            self._bytes += len(payload)
            if not self._armed and not self._arming:
                self._arming = True
                need_arm = True
        if need_arm:
            self._loop.post(self._arm)

    def qsize(self) -> int:
        """Frames pending — the unit the degradation thresholds use."""
        return self._frames

    def pending_bytes(self) -> int:
        return self._bytes

    @property
    def dead(self) -> bool:
        return self._dead

    def request_finish(self) -> None:
        """Flush everything already queued, then set `finished` and
        leave the selector (the old writer-exit sentinel)."""
        need_arm = False
        with self._lock:
            self._finishing = True
            if not self._armed and not self._arming:
                self._arming = True
                need_arm = True
        if need_arm:
            # The arm command notices finishing+empty and tears down
            # (closing the duplicate fd) — an empty queue must not
            # leave the dup fd leaked behind a set `finished`.
            self._loop.post(self._arm)

    def join(self, timeout: Optional[float] = None) -> None:
        self.finished.wait(timeout)

    def kill(self) -> None:
        """Tear the peer out of the pool immediately (socket closing);
        queued frames are dropped. Idempotent, any thread."""
        with self._lock:
            if self._dead:
                return
            self._dead = True
        self._loop.post(self._teardown)

    # --- loop side ---

    def _arm(self) -> None:
        """Loop thread: join the selector's write set (or finish a
        peer whose queue is already drained)."""
        with self._lock:
            self._arming = False
            idle = not self._q and self._sending is None
            if self._dead or (self._finishing and idle):
                done = True
            elif self._armed or idle:
                return
            else:
                self._armed = True
                done = False
        if done:
            self._teardown()
            return
        try:
            self._loop.sel.register(self._wsock, selectors.EVENT_WRITE,
                                    self)
        except (ValueError, KeyError, OSError):
            self._error()

    def _disarm(self) -> None:
        with self._lock:
            if not self._armed:
                return
            self._armed = False
        try:
            self._loop.sel.unregister(self._wsock)
        except (ValueError, KeyError, OSError):
            pass

    def _release_locked(self) -> None:
        """Caller holds `_lock`: final state — mark dead, close the
        duplicate fd (loop-thread-safe: never while armed)."""
        self._dead = True
        self._q.clear()
        self._sending = None
        self._send_off = 0
        self._frames = 0
        self._bytes = 0
        self.finished.set()

    def _teardown(self) -> None:
        self._disarm()
        with self._lock:
            self._release_locked()
        try:
            self._wsock.close()
        except OSError:
            pass
        self._loop.forget(self)

    def _error(self) -> None:
        self._teardown()
        cb = self._on_error
        if cb is not None:
            self._on_error = None  # fire once
            cb(self)

    def _service(self) -> None:
        """Loop thread: push bytes until drained or EWOULDBLOCK. The
        in-flight frame is POPPED into `_sending` before any byte
        moves, so concurrent front-enqueues can never displace it (a
        peek-then-pop here once lost a clock echo and duplicated the
        head frame — caught by the pool-order test)."""
        finishing = False
        while True:
            with self._lock:
                if self._dead:
                    break
                if self._sending is None:
                    if not self._q:
                        self._armed = False
                        finishing = self._finishing
                        break
                    self._sending = self._q.popleft()
                    self._send_off = 0
                    self._fault_done = False
                head = self._sending
                off = self._send_off
            if not self._fault_done and self._fault is not None:
                # Exactly once per FRAME — a zero-byte EWOULDBLOCK on
                # the first attempt must not burn the next frame's
                # seeded rule on re-entry (off would still be 0).
                self._fault_done = True
                verdict = _apply_send_fault(self._fault, self._wsock,
                                            head)
                if verdict == "drop":
                    self._finish_frame(len(head), count=False)
                    continue
                if verdict == "dup":
                    with self._lock:
                        self._q.appendleft(head)
                        self._frames += 1
                        self._bytes += len(head)
                    # fall through: transmit (twice, via the duplicate)
                elif verdict == "error":
                    self._error()
                    return
            try:
                n = self._wsock.send(
                    memoryview(head)[off:] if off else head
                )
            except (BlockingIOError, InterruptedError):
                return  # stays armed; selector will call back
            except OSError:
                self._error()
                return
            if off + n >= len(head):
                self._finish_frame(len(head))
            else:
                with self._lock:
                    self._send_off = off + n
        # Drained (or died): leave the write set.
        try:
            self._loop.sel.unregister(self._wsock)
        except (ValueError, KeyError, OSError):
            pass
        if self._dead:
            self._teardown()
        elif finishing:
            self._teardown()

    def _finish_frame(self, size: int, count: bool = True) -> None:
        """Loop thread: the `_sending` frame fully left (or was
        fault-dropped) — release its slot and its share of the
        bounds."""
        with self._lock:
            self._sending = None
            self._send_off = 0
            self._frames -= 1
            self._bytes -= size
        if count:
            _METRICS.frames.inc()
            tracing.event("wire.send", "wire", bytes=size)


def _is_faulty(sock) -> bool:
    from gol_tpu.testing.faults import FaultySocket

    return isinstance(sock, FaultySocket)


def _apply_send_fault(fsock, wsock, frame: bytes) -> Optional[str]:
    """Consult the seeded plan once per frame — the threaded writers'
    'one sendall per frame' accounting, reproduced on the pool.
    Returns 'drop' / 'dup' / 'error' / None (send normally)."""
    rule = fsock._plan.next_fault(fsock._role, "send")
    if rule is None:
        return None
    if rule.kind == "delay":
        time.sleep(rule.arg)
        return None
    if rule.kind == "drop":
        return "drop"
    if rule.kind == "dup":
        return "dup"
    # reset / partial: the frame dies mid-wire. `partial` pushes half
    # the frame first (best-effort, non-blocking) so the peer sees a
    # torn stream, like the threaded path did.
    if rule.kind == "partial":
        try:
            wsock.send(frame[: max(1, len(frame) // 2)])
        except OSError:
            pass
    fsock._hard_reset()
    return "error"


class _Loop(threading.Thread):
    """One selector thread: a wake pipe for cross-thread commands plus
    every armed peer socket."""

    def __init__(self, name: str):
        super().__init__(name=name, daemon=True)
        self.sel = selectors.DefaultSelector()
        self._rwake, self._wwake = os.pipe()
        os.set_blocking(self._rwake, False)
        os.set_blocking(self._wwake, False)
        self.sel.register(self._rwake, selectors.EVENT_READ, None)
        self._cmds: "collections.deque[Callable[[], None]]" = \
            collections.deque()
        self._stopping = threading.Event()
        #: Peers assigned to this loop (armed or not) — sized gauges
        #: and close() teardown read it.
        self.peers: "set[PoolHandle]" = set()
        self._peers_lock = lockcheck.make_lock("_Loop._peers_lock")

    def adopt(self, handle: PoolHandle) -> None:
        with self._peers_lock:
            self.peers.add(handle)

    def forget(self, handle: PoolHandle) -> None:
        with self._peers_lock:
            self.peers.discard(handle)
        _METRICS.sockets.set(_total_sockets())

    def post(self, fn: Callable[[], None]) -> None:
        self._cmds.append(fn)
        self.wake()

    def wake(self) -> None:
        try:
            os.write(self._wwake, b"x")
        except (BlockingIOError, OSError):
            pass  # pipe full = a wake is already pending

    def stop(self) -> None:
        self._stopping.set()
        self.wake()

    def run(self) -> None:
        while not self._stopping.is_set():
            try:
                events = self.sel.select(timeout=0.5)
            except OSError:
                events = []
            t0 = time.perf_counter()
            while self._cmds:
                try:
                    self._cmds.popleft()()
                except Exception:  # a peer's error path must not kill
                    pass           # every OTHER peer's writer
            for key, _ in events:
                if key.data is None:
                    try:
                        os.read(self._rwake, 4096)
                    except (BlockingIOError, OSError):
                        pass
                    continue
                try:
                    key.data._service()
                except Exception:
                    # A peer's error path must not kill every OTHER
                    # peer's writer.
                    with contextlib.suppress(Exception):
                        key.data._error()
            dt = time.perf_counter() - t0
            if events or self._cmds:
                _METRICS.busy_seconds.inc(dt)
        # Teardown: every peer leaves with its duplicate fd closed.
        with self._peers_lock:
            peers = list(self.peers)
        for p in peers:
            p._teardown()
        self.sel.close()
        for fd in (self._rwake, self._wwake):
            try:
                os.close(fd)
            except OSError:
                pass


#: Registered-socket census across every live pool in the process
#: (the gauge is process-global; pools are per server/relay).
_POOLS: "list[WriterPool]" = []
_POOLS_LOCK = lockcheck.make_lock("writerpool:_POOLS_LOCK")


def _total_sockets() -> int:
    with _POOLS_LOCK:
        pools = list(_POOLS)
    return sum(p.sockets() for p in pools)


class WriterPool:
    """N selector loops; peers are assigned round-robin at register."""

    #: Default per-peer byte bound: enough for a full 8192² board
    #: raster plus headroom — the hard stop a frame-count bound alone
    #: cannot provide (1024 queued rasters would be gigabytes).
    MAX_BYTES = 256 << 20

    def __init__(self, threads: int = 2, name: str = "gol-writer-pool"):
        self._loops = [
            _Loop(f"{name}-{i}") for i in range(max(1, int(threads)))
        ]
        for lp in self._loops:
            lp.start()
        self._rr = itertools.count()
        self._closed = False
        with _POOLS_LOCK:
            _POOLS.append(self)

    @property
    def threads(self) -> int:
        return len(self._loops)

    def register(self, sock, on_error=None, *,
                 max_frames: int = 1024,
                 max_bytes: Optional[int] = None) -> PoolHandle:
        if self._closed:
            raise RuntimeError("writer pool is closed")
        loop = self._loops[next(self._rr) % len(self._loops)]
        handle = PoolHandle(loop, sock, on_error, max_frames,
                            max_bytes if max_bytes is not None
                            else self.MAX_BYTES)
        loop.adopt(handle)
        _METRICS.sockets.set(_total_sockets())
        return handle

    def sockets(self) -> int:
        return sum(len(lp.peers) for lp in self._loops)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        with _POOLS_LOCK:
            if self in _POOLS:
                _POOLS.remove(self)
        for lp in self._loops:
            lp.stop()
        for lp in self._loops:
            lp.join(timeout=5)
        _METRICS.sockets.set(_total_sockets())
