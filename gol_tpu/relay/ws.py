"""Stdlib RFC-6455 WebSocket framing — the browser edge of the relay.

The "millions of users" surface is browsers, and browsers speak
WebSocket, not length-prefixed TCP frames. This module is the minimal
server side of RFC 6455, stdlib only, shaped for the relay's
zero-re-encode invariant: every gol_tpu wire frame payload rides
UNCHANGED inside one WS binary message (the 4-byte length prefix is
dropped — WS frames self-delimit), so a browser observer receives the
IDENTICAL bytes a TCP observer would, and a JS client decodes them
with the same tag-dispatch the Python client uses.

Subprotocol (`gol-tpu-wire`): after the HTTP upgrade, the client's
first message is the hello JSON (text or binary); everything after is
the ordinary message catalog (wire.py) minus framing. Control mapping:

- WS ping (server → client) IS the heartbeat beacon — the payload
  carries the committed turn as ASCII digits; the browser's automatic
  pong is the liveness refresh (PR 3's hb/pong plane with zero client
  JS).
- WS close ends the stream (the "bye" of the WS world; a "bye" JSON
  still precedes it so portable clients need no special casing).

Server-side enforcement (the RFC's masking rules, pinned by the fuzz
sweep): client frames MUST be masked, server frames MUST NOT be;
control frames must be FIN, unfragmented and <= 125 bytes; unknown
opcodes, oversized messages and malformed headers fail the connection
cleanly — the reader surfaces `WSError`, the relay detaches the peer,
nothing else dies.

Raw-socket reads live ONLY in `_read_exact` (this module's sanctioned
read primitive — the blocking-io-timeout lint treats it like
wire._recv_exact): an idle read deadline surfaces as TimeoutError at
a frame boundary, WSError mid-frame.
"""

from __future__ import annotations

import base64
import hashlib
import os
import socket
import struct
from typing import Optional, Tuple

from gol_tpu.distributed.wire import MAX_FRAME

__all__ = [
    "GUID",
    "OP_BINARY",
    "OP_CLOSE",
    "OP_PING",
    "OP_PONG",
    "OP_TEXT",
    "WSError",
    "accept_key",
    "encode_frame",
    "handshake",
    "read_message",
]

GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"
SUBPROTOCOL = "gol-tpu-wire"

OP_CONT, OP_TEXT, OP_BINARY = 0x0, 0x1, 0x2
OP_CLOSE, OP_PING, OP_PONG = 0x8, 0x9, 0xA

#: Message-size ceiling: the TCP wire's own frame cap — a WS peer can
#: carry anything a TCP peer could, nothing bigger.
MAX_MESSAGE = MAX_FRAME

#: HTTP request-head ceiling for the upgrade (headers only — a hostile
#: peer must not feed us an unbounded preamble).
MAX_REQUEST = 16 << 10

#: Fragments one message may arrive in (fragmentation is legal; an
#: unbounded fragment train is an attack).
MAX_FRAGMENTS = 256


class WSError(ConnectionError):
    """Protocol violation or malformed frame — the connection is
    unrecoverable (stream position lost), the peer detaches cleanly."""


def accept_key(key: str) -> str:
    """Sec-WebSocket-Accept for a client's Sec-WebSocket-Key."""
    digest = hashlib.sha1((key + GUID).encode("ascii")).digest()
    return base64.b64encode(digest).decode("ascii")


def _read_exact(sock: socket.socket, n: int) -> bytes:
    """THE raw read primitive of the WS plane (the wire._recv_exact
    discipline): deadline expiry with zero bytes is idleness
    (TimeoutError), mid-frame expiry or EOF is a broken peer."""
    buf = bytearray()
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except TimeoutError:
            if not buf:
                raise
            raise WSError("read deadline expired mid-frame") from None
        if not chunk:
            raise WSError("connection closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)


def handshake(sock: socket.socket) -> dict:
    """Serve one HTTP upgrade: parse the request head, validate the
    WebSocket headers, send the 101 response (echoing the gol-tpu-wire
    subprotocol when offered). Returns the lowercased header map.
    Raises WSError on anything malformed — the caller closes."""
    head = bytearray()
    while b"\r\n\r\n" not in head:
        if len(head) > MAX_REQUEST:
            raise WSError("oversized upgrade request")
        try:
            chunk = sock.recv(4096)
        except TimeoutError:
            raise WSError("upgrade request timed out") from None
        if not chunk:
            raise WSError("connection closed during upgrade")
        head.extend(chunk)
    try:
        text = bytes(head).split(b"\r\n\r\n", 1)[0].decode("latin-1")
    except UnicodeDecodeError:  # pragma: no cover - latin-1 total
        raise WSError("undecodable upgrade request") from None
    lines = text.split("\r\n")
    parts = lines[0].split(" ")
    if len(parts) < 3 or parts[0] != "GET":
        raise WSError(f"not a websocket GET: {lines[0]!r}")
    headers: dict = {"_path": parts[1]}
    for line in lines[1:]:
        if ":" not in line:
            continue
        k, v = line.split(":", 1)
        headers[k.strip().lower()] = v.strip()
    if "websocket" not in headers.get("upgrade", "").lower():
        raise WSError("missing Upgrade: websocket")
    key = headers.get("sec-websocket-key")
    if not key:
        raise WSError("missing Sec-WebSocket-Key")
    resp = [
        "HTTP/1.1 101 Switching Protocols",
        "Upgrade: websocket",
        "Connection: Upgrade",
        f"Sec-WebSocket-Accept: {accept_key(key)}",
    ]
    offered = [p.strip() for p in
               headers.get("sec-websocket-protocol", "").split(",")]
    if SUBPROTOCOL in offered:
        resp.append(f"Sec-WebSocket-Protocol: {SUBPROTOCOL}")
    sock.sendall(("\r\n".join(resp) + "\r\n\r\n").encode("ascii"))
    return headers


def encode_frame(opcode: int, payload: bytes, fin: bool = True,
                 mask: bool = False) -> bytes:
    """One WS frame. Server→client frames are unmasked (the RFC
    REQUIRES it); mask=True builds a client-side frame — the test
    client and the fuzz suite use it."""
    b0 = (0x80 if fin else 0) | (opcode & 0x0F)
    n = len(payload)
    mbit = 0x80 if mask else 0
    if n < 126:
        header = struct.pack("!BB", b0, mbit | n)
    elif n < (1 << 16):
        header = struct.pack("!BBH", b0, mbit | 126, n)
    else:
        header = struct.pack("!BBQ", b0, mbit | 127, n)
    if not mask:
        return header + payload
    key = os.urandom(4)
    masked = bytes(b ^ key[i % 4] for i, b in enumerate(payload))
    return header + key + masked


def _read_frame(sock: socket.socket,
                require_mask: bool) -> Tuple[int, bool, bytes]:
    """(opcode, fin, payload) of one raw frame; server side demands
    masked client frames and bounds every length."""
    h = _read_exact(sock, 2)
    fin = bool(h[0] & 0x80)
    if h[0] & 0x70:
        raise WSError("RSV bits set without a negotiated extension")
    opcode = h[0] & 0x0F
    masked = bool(h[1] & 0x80)
    n = h[1] & 0x7F
    if require_mask and not masked:
        # The RFC is explicit: a server MUST fail the connection on
        # an unmasked client frame (proxy-cache poisoning defence).
        raise WSError("unmasked client frame")
    if opcode >= OP_CLOSE:
        # Control frames: FIN, never fragmented, tiny.
        if not fin:
            raise WSError("fragmented control frame")
        if n > 125:
            raise WSError("oversized control frame")
    if n == 126:
        (n,) = struct.unpack("!H", _read_exact(sock, 2))
    elif n == 127:
        (n,) = struct.unpack("!Q", _read_exact(sock, 8))
    if n > MAX_MESSAGE:
        raise WSError(f"frame of {n} bytes exceeds {MAX_MESSAGE}")
    key = _read_exact(sock, 4) if masked else b""
    payload = _read_exact(sock, n) if n else b""
    if masked and n:
        # Vectorized unmask: a per-byte Python loop at the 64 MB
        # message cap would be a GIL-holding CPU-exhaustion gift to
        # any hostile peer.
        import numpy as np

        data = np.frombuffer(payload, np.uint8) ^ np.frombuffer(
            (key * ((n + 3) // 4))[:n], np.uint8
        )
        payload = data.tobytes()
    return opcode, fin, payload


def read_message(sock: socket.socket,
                 require_mask: bool = True,
                 on_control=None) -> Tuple[int, Optional[bytes]]:
    """Next complete MESSAGE: (opcode, payload). Handles continuation
    fragments (returned under the initial opcode). Control frames at
    a message boundary return as their own messages; a control frame
    INTERLEAVED between fragments (legal — RFC 6455 §5.4) goes to
    `on_control(op, payload)` so the fragment buffer survives (close
    still returns immediately — the connection is ending either way);
    without a callback, interleaved pings/pongs are dropped. Raises
    WSError on every protocol violation, TimeoutError on an idle
    deadline at a message boundary."""
    opcode = None
    parts: list = []
    total = 0
    while True:
        try:
            op, fin, payload = _read_frame(sock, require_mask)
        except TimeoutError:
            if opcode is not None:
                # Mid-MESSAGE idleness: the fragment buffer would be
                # silently lost if this surfaced as boundary idleness
                # — the stream is unrecoverable, say so.
                raise WSError(
                    "read deadline expired between fragments"
                ) from None
            raise
        if op in (OP_CLOSE, OP_PING, OP_PONG):
            if op != OP_CLOSE and opcode is not None:
                # Interleaved mid-fragmentation: hand to the caller's
                # hook (or drop) — returning it would discard the
                # buffered fragments and then kill the conformant
                # peer on its continuation.
                if on_control is not None:
                    on_control(op, payload)
                continue
            return op, payload
        if op == OP_CONT:
            if opcode is None:
                raise WSError("continuation frame with nothing to continue")
        elif op in (OP_TEXT, OP_BINARY):
            if opcode is not None:
                raise WSError("new data frame inside a fragmented message")
            opcode = op
        else:
            raise WSError(f"unknown opcode {op:#x}")
        parts.append(payload)
        total += len(payload)
        if total > MAX_MESSAGE:
            raise WSError("fragmented message exceeds the size cap")
        if len(parts) > MAX_FRAGMENTS:
            raise WSError("fragment train exceeds the cap")
        if fin:
            return opcode, b"".join(parts)


def close_frame(code: int = 1000, reason: str = "") -> bytes:
    payload = struct.pack("!H", code) + reason.encode("utf-8")[:100]
    return encode_frame(OP_CLOSE, payload)
