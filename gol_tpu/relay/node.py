"""Relay node — store-and-forward fan-out with zero re-encode.

One engine cannot talk to 10⁵–10⁶ watchers directly: even with
encode-once batching (PR 10) the root still pays O(peers) queue pushes
AND holds every TCP connection. A depth-log broadcast TREE is the
standard answer (every CDN and pub-sub system converges on it), and
the _TAG_FBATCH frames are deliberately self-contained — so a relay
is a BYTE-COPY problem, not an encode problem:

- UPSTREAM the relay attaches exactly like a batching binary client
  (hello binary+batch, observe role): it receives FBATCH frames, board
  syncs, heartbeats. PR 3 reconnect+backoff and PR 5 clock sync
  compose PER HOP — the relay re-syncs its clock against its upstream
  and answers downstream probes with its own clock PLUS that offset,
  so offsets sum along the path and a leaf's latency readings are
  against the ROOT's emit stamps.
- DOWNSTREAM it re-serves N observers on the same wire protocol,
  forwarding the IDENTICAL frame bytes (`wire.recv_frame` keeps the
  raw payload; `_Conn.send_raw` length-prefixes the same bytes — no
  encoder runs per peer, ever). Only per-stream state is local: each
  downstream's BoardSync (encoded from the relay's shadow raster at
  attach/recovery) and its synced_turn gate.
- The PR 7 degradation machinery runs per downstream on the writer
  pool's queues: a wedged observer sheds FRAMES (whole batches), is
  made whole by ONE coalescing BoardSync from the shadow raster when
  it drains, and is evicted only past the drain deadline.
- The WebSocket gateway (`relay.ws`, CLI --ws-port) is a leaf tier on
  the same abstraction: browser observers get the identical binary
  payloads inside WS binary messages, pings carry the heartbeat
  plane.

A relay's /metrics sidecar exports depth/upstream labels
(`gol_tpu_relay_depth`, `gol_tpu_relay_node_info{listen,upstream}`)
so `obs.console` renders the whole tree from scrapes alone.
"""

from __future__ import annotations

import contextlib
import hmac
import json
import logging
import random
import socket
import threading
import time
from typing import Optional

import numpy as np

from gol_tpu import obs
from gol_tpu.distributed import wire
from gol_tpu.distributed.client import apply_fbatch_raster, \
    sanitize_retry_after
from gol_tpu.distributed.server import (
    _Conn,
    _forget_peer_usage,
    install_lag_gauge,
    remove_lag_gauge,
)
from gol_tpu.obs import accounting, flight, tracing
from gol_tpu.obs.freshness import ServerFreshness, sane_lag
from gol_tpu.relay import ws as wsproto
from gol_tpu.relay.writerpool import WriterPool
from gol_tpu.analysis.concurrency import lockcheck

__all__ = ["RelayNode", "WSConn"]

log = logging.getLogger(__name__)


class _RelayMetrics:
    def __init__(self):
        self.depth = obs.gauge(
            "gol_tpu_relay_depth",
            "Hops from the root engine (root serves depth 0; a relay "
            "attached to it is depth 1)",
        )
        self.peers = obs.gauge(
            "gol_tpu_relay_peers", "Downstream observers attached",
        )
        self.ws_peers = obs.gauge(
            "gol_tpu_relay_ws_peers",
            "Downstream observers attached over WebSocket",
        )
        self.forwarded = obs.counter(
            "gol_tpu_relay_forwarded_frames_total",
            "Stream frames forwarded downstream (byte-identical, "
            "zero re-encode)",
        )
        self.forwarded_bytes = obs.counter(
            "gol_tpu_relay_forwarded_bytes_total",
            "Payload bytes forwarded downstream",
        )
        self.reconnects = obs.counter(
            "gol_tpu_relay_upstream_reconnects_total",
            "Successful upstream re-dial + re-sync cycles",
        )
        self.clock_offset = obs.gauge(
            "gol_tpu_relay_clock_offset_seconds",
            "Estimated offset of THIS hop's upstream clock chain "
            "(upstream-advertised time - local time; offsets sum "
            "along the relay path)",
        )
        self.rtt = obs.gauge(
            "gol_tpu_relay_upstream_rtt_seconds",
            "Min round-trip of the upstream clock probe — this hop's "
            "added latency is about half of it",
        )
        self.rejects = obs.counter(
            "gol_tpu_relay_rejects_total",
            "Downstream attaches rejected (bad hello, capability "
            "mismatch, capacity, auth)",
        )
        self.repoints = obs.counter(
            "gol_tpu_relay_repoints_total",
            "Upstream re-point verbs applied (control plane heal: the "
            "old link is torn down and the node re-attaches to a new "
            "upstream with a fresh BoardSync)",
        )
        self.forward_latency = obs.histogram(
            "gol_tpu_relay_forward_latency_seconds",
            "Root emit stamp -> frame arrival at THIS hop, on the "
            "summed per-hop corrected clock — successive tiers' "
            "readings decompose emit->leaf-apply into per-hop legs "
            "(docs/OBSERVABILITY.md \"Freshness plane\")",
        )


_METRICS = _RelayMetrics()


class WSConn(_Conn):
    """A downstream peer speaking RFC-6455: the identical wire frame
    payloads ride inside WS BINARY messages (no length prefix — WS
    frames self-delimit), and the heartbeat beacon is a WS ping whose
    automatic browser pong refreshes liveness."""

    def _wrap(self, payload: bytes) -> bytes:
        return wsproto.encode_frame(wsproto.OP_BINARY, payload)

    def beacon(self, turn: int) -> None:
        # Ping payload: the committed turn as ASCII — visible in any
        # browser devtools, ignorable by the auto-pong.
        frame = wsproto.encode_frame(wsproto.OP_PING,
                                     str(turn).encode("ascii"))
        if self._handle is not None:
            self._handle.enqueue(frame)
        else:
            with self._lock:
                self.sock.sendall(frame)

    def enqueue_control(self, frame: bytes) -> None:
        """Raw WS control frame (pong, close), front of the queue."""
        if self._handle is not None:
            with contextlib.suppress(Exception):
                self._handle.enqueue(frame, front=True)
        else:
            with self._lock, contextlib.suppress(OSError):
                self.sock.sendall(frame)


class RelayNode:
    """Attach upstream as one batching client; re-serve N downstream
    observers (TCP and WebSocket) with zero re-encode."""

    HELLO_TIMEOUT = 10.0
    DRAIN_TIMEOUT = 5.0
    HB_MISS_LIMIT = 3
    CLOCK_PROBES = 8

    def __init__(
        self,
        upstream: "tuple[str, int]",
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        secret: Optional[str] = None,
        session: Optional[str] = None,
        batch_turns: int = 1024,
        heartbeat_secs: float = 2.0,
        evict_secs: Optional[float] = None,
        max_peers: Optional[int] = None,
        high_water: Optional[int] = None,
        drain_secs: Optional[float] = None,
        retry_after_secs: float = 1.0,
        writer_pool_threads: int = 2,
        ws_host: Optional[str] = None,
        ws_port: Optional[int] = None,
        reconnect_window: float = 60.0,
        reconnect_seed: Optional[int] = None,
        dial_timeout: float = 30.0,
    ):
        self.upstream = (upstream[0], int(upstream[1]))
        self._secret = secret
        self._session = session
        self.batch_turns = max(1, int(batch_turns))
        self.heartbeat_secs = max(0.0, heartbeat_secs)
        self.evict_secs = (evict_secs if evict_secs is not None
                           else 3.0 * self.heartbeat_secs)
        self.max_peers = max_peers
        self.high_water = high_water
        self.drain_secs = drain_secs
        self.retry_after_secs = max(0.0, retry_after_secs)
        self._window = reconnect_window
        self._rng = random.Random(reconnect_seed)
        self._dial_timeout = dial_timeout
        self._listener = socket.create_server((host, port))
        self.address = self._listener.getsockname()
        self._ws_listener = None
        if ws_port is not None:
            self._ws_listener = socket.create_server(
                (ws_host or host, ws_port)
            )
            self.ws_address = self._ws_listener.getsockname()
        else:
            self.ws_address = None
        for addr in (self.address, self.ws_address):
            if addr is not None and (
                self.upstream[1] == addr[1]
                and self.upstream[0] in (addr[0], "localhost")
            ):
                self._listener.close()
                if self._ws_listener is not None:
                    self._ws_listener.close()
                raise ValueError(
                    f"relay upstream {self.upstream} loops back to its "
                    "own listener — a relay cannot feed itself"
                )
        # The pool LAST: every earlier constructor failure (loopback
        # refusal, EADDRINUSE) must not leak its loop threads.
        self.pool = WriterPool(writer_pool_threads, "gol-relay-writer")
        #: Shadow raster + committed turn, advanced by every upstream
        #: frame under `_board_lock` — what a NEW downstream observer
        #: board-syncs from (the one per-stream thing a relay encodes).
        self.board: Optional[np.ndarray] = None
        self.turn = 0
        self._board_lock = lockcheck.make_lock("RelayNode._board_lock")
        #: Hops from the root: upstream's attach-ack depth + 1.
        self.depth = 1
        #: Negotiated upstream max-k (the granularity our downstream
        #: frames arrive at — re-advertised in our attach-acks).
        self.upstream_batch = 0
        #: Summed clock offset to the ROOT (upstream echoes are
        #: already root-adjusted by the upstream relay, recursively).
        self.clock_offset: Optional[float] = None
        self.upstream_rtt: Optional[float] = None
        self._clk_samples: "list[tuple[float, float]]" = []
        self._clk_left = 0
        self._up_sock: Optional[socket.socket] = None
        self._up_lock = lockcheck.make_lock(
            "RelayNode._up_lock")  # serializes upstream sends
        self._up_hb_secs = 0.0
        self.reconnects = 0
        self.synced = threading.Event()
        #: Set by repoint(): the upstream loop treats the next link
        #: death as a FRESH start (attempt/window reset) — a re-point
        #: is an operator action, not a failure of the new target.
        self._repointed = threading.Event()
        self._conns: "list[_Conn]" = []
        self._conn_lock = lockcheck.make_lock("RelayNode._conn_lock")
        self._shutdown = threading.Event()
        self.done = threading.Event()
        self._threads: "list[threading.Thread]" = []
        #: Freshness plane: downstream peers age against the relay's
        #: shadow turn (advanced by every upstream frame).
        self.freshness = ServerFreshness("relay")
        _METRICS.depth.set(self.depth)
        self._info_gauge()

    def _info_labels(self) -> dict:
        return {"listen": f"{self.address[0]}:{self.address[1]}",
                "upstream": f"{self.upstream[0]}:{self.upstream[1]}"}

    def _info_gauge(self) -> None:
        obs.gauge(
            "gol_tpu_relay_node_info",
            "Relay identity (value 1): this node's serving address "
            "and its upstream — obs.console joins these into the "
            "fan-out tree",
            self._info_labels(),
        ).set(1)

    # --- lifecycle ---

    def start(self) -> "RelayNode":
        loops = [(self._upstream_loop, "gol-relay-upstream"),
                 (self._accept_loop, "gol-relay-accept")]
        if self._ws_listener is not None:
            loops.append((self._ws_accept_loop, "gol-relay-ws-accept"))
        if self.heartbeat_secs > 0:
            loops.append((self._heartbeat_loop, "gol-relay-heartbeat"))
        for fn, name in loops:
            t = threading.Thread(target=fn, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def shutdown(self) -> None:
        if self._shutdown.is_set():
            self.done.wait(timeout=1.0)
            return
        self._shutdown.set()
        for lst in (self._listener, self._ws_listener):
            if lst is not None:
                with contextlib.suppress(OSError):
                    # Wake any thread parked in accept() (see the
                    # servers' shutdown note) before closing.
                    lst.shutdown(socket.SHUT_RDWR)
                with contextlib.suppress(OSError):
                    lst.close()
        with contextlib.suppress(OSError):
            if self._up_sock is not None:
                self._up_sock.close()
        with self._conn_lock:
            conns, self._conns = list(self._conns), []
        for conn in conns:
            with contextlib.suppress(Exception):
                conn.send({"t": "bye"})
            if isinstance(conn, WSConn):
                with contextlib.suppress(Exception):
                    conn.enqueue_control(wsproto.close_frame())
            conn.request_finish()
        deadline = time.monotonic() + self.DRAIN_TIMEOUT
        for conn in conns:
            conn.join_writer(max(0.1, deadline - time.monotonic()))
            conn.close()
        self.pool.close()
        # Evict the per-instance info child: ephemeral-port relays
        # constructed in one process (tests, embedders) must not
        # accumulate dead tree roots in the process-global registry.
        obs.registry().remove("gol_tpu_relay_node_info",
                              self._info_labels())
        self.freshness.close()
        self.done.set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self.done.wait(timeout)

    def health(self) -> dict:
        with self._conn_lock:
            peers = len(self._conns)
        return {
            "status": ("shutting-down" if self._shutdown.is_set()
                       else "ok" if self.synced.is_set()
                       else "attaching"),
            "role": "relay",
            "depth": self.depth,
            "upstream": f"{self.upstream[0]}:{self.upstream[1]}",
            "address": list(self.address),
            "turn": self.turn,
            "peers": peers,
            "reconnects": self.reconnects,
        }

    def repoint(self, addr: "tuple[str, int]") -> dict:
        """Re-point the upstream link at a NEW address (control plane
        heal, PR 18): tear the current link, swap `self.upstream`, and
        let the supervised `_upstream_loop` re-dial the new target with
        a FRESH reconnect window and a fresh BoardSync. Downstream
        peers never notice beyond the same brief stall an ordinary
        upstream reconnect causes — their frames resume byte-exact
        once the new upstream's board sync lands.

        Returns {"upstream": "host:port", "changed": bool}; raises
        ValueError for an address that would make the relay feed
        itself (same guard as the constructor)."""
        new = (str(addr[0]), int(addr[1]))
        for own in (self.address, self.ws_address):
            if own is not None and (
                new[1] == own[1] and new[0] in (own[0], "localhost")
            ):
                raise ValueError(
                    f"repoint target {new} loops back to this relay's "
                    "own listener — a relay cannot feed itself"
                )
        with self._up_lock:
            changed = new != self.upstream
            old_labels = self._info_labels()
            self.upstream = new
            sock, self._up_sock = self._up_sock, None
        if changed:
            # Swap the info-gauge child BEFORE the re-dial: the
            # console/controller tree join must see the new edge on
            # the very next scrape, not after the link comes up.
            obs.registry().remove("gol_tpu_relay_node_info", old_labels)
            self._info_gauge()
            self.clock_offset = None
            self.upstream_rtt = None
            _METRICS.repoints.inc()
            tracing.event("relay.repoint", "lifecycle",
                          upstream=f"{new[0]}:{new[1]}")
            flight.note("relay.repoint", upstream=f"{new[0]}:{new[1]}")
        self.synced.clear()
        self._repointed.set()
        if sock is not None:
            # Killing the socket makes _forward_stream raise; the
            # supervised loop then re-dials self.upstream — which now
            # names the new target. Works identically when the loop is
            # parked in a backoff wait (the _repointed flag resets its
            # attempt counter and window).
            with contextlib.suppress(OSError):
                sock.shutdown(socket.SHUT_RDWR)
            with contextlib.suppress(OSError):
                sock.close()
        return {"upstream": f"{new[0]}:{new[1]}", "changed": changed}

    # --- upstream: one batching binary client ---

    def _dial_upstream(self) -> socket.socket:
        from gol_tpu.testing import faults

        sock = faults.wrap("client", socket.create_connection(
            self.upstream, timeout=self._dial_timeout
        ))
        sock.settimeout(self._dial_timeout)
        hello = {"t": "hello", "want_flips": True, "binary": True,
                 "compact": True, "hb": True, "delta": False,
                 "role": "observe", "batch": self.batch_turns,
                 "relay": True}
        if self._session is not None:
            hello["session"] = self._session
        if self._secret is not None:
            hello["secret"] = self._secret
        wire.send_msg(sock, hello)
        first = wire.recv_msg(sock, allow_binary=False)
        if first is None:
            raise wire.WireError("upstream closed during handshake")
        if first.get("t") == "error":
            reason = first.get("reason", "rejected")
            hint = sanitize_retry_after(first.get("retry_after"))
            raise _UpstreamRejected(reason, hint)
        if first.get("t") != "attach-ack":
            raise wire.WireError(f"unexpected first reply: {first!r}")
        self._up_hb_secs = float(first.get("hb_secs", 0) or 0)
        self.depth = int(first.get("depth", 0)) + 1
        _METRICS.depth.set(self.depth)
        self.upstream_batch = int(first.get("batch", 0) or 0)
        # Streaming deadline: three missed beacons = upstream is gone
        # (PR 3's client discipline, per hop).
        sock.settimeout(3.0 * self._up_hb_secs
                        if self._up_hb_secs > 0 else None)
        if first.get("clock"):
            self._clk_samples = []
            self._clk_left = self.CLOCK_PROBES
            # Directly on the dialing socket: _up_sock is only
            # installed after this returns, so _send_up would no-op
            # and the probe chain (echo-driven) would never start.
            with contextlib.suppress(OSError, ConnectionError,
                                     wire.WireError):
                with self._up_lock:
                    wire.send_msg(sock, {"t": "clk", "t0": time.time()})
        return sock

    def _send_up(self, msg: dict) -> None:
        with contextlib.suppress(OSError, ConnectionError,
                                 wire.WireError):
            with self._up_lock:
                if self._up_sock is not None:
                    wire.send_msg(self._up_sock, msg)

    def _upstream_loop(self) -> None:
        """Supervised forwarder: read raw frames, advance the shadow,
        fan identical bytes out; on link death, re-dial with backoff
        and resume through the upstream's BoardSync."""
        attempt = 0
        deadline = None  # armed on first failure
        while not self._shutdown.is_set():
            try:
                sock = self._dial_upstream()
            except _UpstreamRejected as e:
                if e.reason in ("unauthorized", "unknown-session"):
                    log.error("upstream rejected relay: %s", e.reason)
                    break  # policy: not retryable
                delay = (e.retry_after
                         if e.retry_after is not None else None)
                attempt, deadline, dead = self._backoff(
                    attempt, deadline, delay)
                if dead:
                    break
                continue
            except (wire.WireError, ConnectionError, OSError,
                    TimeoutError) as e:
                attempt, deadline, dead = self._backoff(
                    attempt, deadline, None)
                if dead:
                    break
                log.warning("upstream dial failed (%s) — retrying", e)
                continue
            self._up_sock = sock
            if self._repointed.is_set():
                # A repoint landed while this dial was in flight: the
                # socket may still point at the OLD upstream. Drop it
                # and re-dial — self.upstream now names the new target.
                self._repointed.clear()
                with contextlib.suppress(OSError):
                    sock.close()
                self._up_sock = None
                attempt, deadline = 0, None
                continue
            if attempt:
                self.reconnects += 1
                _METRICS.reconnects.inc()
                tracing.event("relay.reconnected", "lifecycle",
                              attempt=attempt)
                flight.note("relay.reconnected", attempt=attempt)
            attempt, deadline = 0, None
            try:
                self._forward_stream(sock)
                break  # clean end of stream (bye)
            except TimeoutError:
                reason = "upstream heartbeat deadline expired"
            except (wire.WireError, OSError, ConnectionError) as e:
                reason = str(e) or type(e).__name__
            if self._shutdown.is_set():
                break
            tracing.event("relay.link_down", "lifecycle", reason=reason)
            flight.note("relay.link_down", reason=reason)
            log.warning("upstream link failed (%s) — reconnecting",
                        reason)
            with contextlib.suppress(OSError):
                sock.close()
            self._up_sock = None
            attempt = 1
            deadline = time.monotonic() + self._window
        self.shutdown()

    def _backoff(self, attempt, deadline, hint):
        """One supervised retry wait; returns (attempt, deadline,
        exhausted)."""
        if self._repointed.is_set():
            # A repoint landed mid-backoff: the NEW target deserves a
            # fresh attempt counter and window, whatever the old
            # address had burned dialing a dead upstream.
            self._repointed.clear()
            attempt, deadline = 0, None
        if deadline is None:
            deadline = time.monotonic() + self._window
        if hint is not None:
            delay = hint * (0.9 + 0.2 * self._rng.random())
        else:
            delay = min(2.0, 0.05 * (2 ** min(attempt, 10)))
            delay *= 0.5 + self._rng.random()
        if time.monotonic() + delay >= deadline:
            log.error("upstream reconnect window exhausted")
            return attempt, deadline, True
        if self._shutdown.wait(delay):
            return attempt, deadline, True
        return attempt + 1, deadline, False

    #: Message kinds consumed at this hop, never forwarded: the relay
    #: runs its own heartbeat/clock planes per hop, and handshake
    #: replies are per-link.
    _HOP_LOCAL = ("attach-ack", "clk", "hb", "error", "detached")

    def _forward_stream(self, sock) -> None:
        while True:
            payload = wire.recv_frame(sock)
            if payload is None:
                raise wire.WireError(
                    "upstream closed the stream without a goodbye"
                )
            msg = wire.parse_payload(payload)
            t = msg.get("t")
            if t in self._HOP_LOCAL:
                self._handle_hop_local(msg)
                continue
            if t == "board":
                self._on_upstream_board(msg, payload)
                continue
            if t == "fbatch":
                # Per-hop forward latency: the frame's root emit stamp
                # against THIS hop's arrival, on the summed corrected
                # clock — hostile/absurd stamps are dropped, never
                # observed (sane_lag; the wire fuzz pin).
                lag = sane_lag(msg.get("ts"),
                               time.time() + (self.clock_offset or 0.0))
                if lag is not None:
                    _METRICS.forward_latency.observe(lag)
                with self._board_lock:
                    if self.board is None:
                        raise wire.WireError(
                            "batch frame before any upstream board sync"
                        )
                    apply_fbatch_raster(self.board, msg, self.turn)
                    self.turn = max(
                        self.turn,
                        int(msg["first_turn"]) + int(msg["k"]) - 1,
                    )
                    self.freshness.note_commit(self.turn)
                    self._forward(payload,
                                  last_turn=int(msg["first_turn"])
                                  + int(msg["k"]) - 1, flips=True)
                continue
            if t == "flips":
                # Per-turn coordinate frames (a root whose engine is
                # not in chunk mode): self-contained, forwardable.
                with self._board_lock:
                    if self.board is not None \
                            and msg["turn"] > self.turn:
                        coords = np.asarray(msg["coords"]).reshape(-1, 2)
                        if len(coords):
                            self.board[coords[:, 1], coords[:, 0]] ^= \
                                np.uint8(255)
                        self.turn = int(msg["turn"])
                    self._forward(payload, last_turn=int(msg["turn"]),
                                  flips=True)
                continue
            if t == "ev" and msg.get("k") == "turn":
                lag = sane_lag(msg.get("ts"),
                               time.time() + (self.clock_offset or 0.0))
                if lag is not None:
                    _METRICS.forward_latency.observe(lag)
                with self._board_lock:
                    self.turn = max(self.turn, int(msg.get("turn", 0)))
                    self.freshness.note_commit(self.turn)
                    self._forward(payload,
                                  last_turn=int(msg.get("turn", 0)))
                continue
            # Everything else — alive ticks, state changes, finals,
            # unknown future kinds — forwards verbatim (a relay is
            # transparent to stream content it does not interpret).
            with self._board_lock:
                self._forward(payload, last_turn=None,
                              control=t in ("ev", "bye"))
            if t == "bye":
                return  # upstream run over: propagate and finish

    def _handle_hop_local(self, msg: dict) -> None:
        t = msg.get("t")
        if t == "hb":
            self._send_up({"t": "hb"})
        elif t == "clk":
            self._on_clk_echo(msg)

    def _on_clk_echo(self, msg: dict) -> None:
        if self._clk_left <= 0:
            return
        t1 = time.time()
        try:
            pt0, ts = float(msg["t0"]), float(msg["ts"])
        except (KeyError, TypeError, ValueError):
            return
        rtt = max(0.0, t1 - pt0)
        self._clk_samples.append((rtt, ts - (pt0 + t1) / 2.0))
        self._clk_left -= 1
        if self._clk_left > 0:
            self._send_up({"t": "clk", "t0": time.time()})
            return
        rtt, off = min(self._clk_samples)
        if abs(off) <= rtt / 2.0:
            off = 0.0  # zero is inside the error bound (PR 5 rule)
        self.clock_offset = off
        self.upstream_rtt = rtt
        _METRICS.clock_offset.set(off)
        _METRICS.rtt.set(rtt)
        # The relay's trace dump joins merged timelines on the ROOT's
        # timebase (upstream echoes are already root-adjusted, so the
        # summed offset is exactly report merge's correction) — what
        # makes the per-hop `turn.forward` marks decomposable.
        tracing.set_clock_offset(off)
        tracing.event("relay.clock_sync", "lifecycle",
                      offset_s=round(off, 6), rtt_s=round(rtt, 6))

    def _on_upstream_board(self, msg: dict, payload: bytes) -> None:
        """Upstream BoardSync (attach, reconnect resync, or upstream
        degradation recovery): replace the shadow and make EVERY
        downstream whole with the same bytes — the sync frame is
        control-plane (never shed) and synced_turn-gates whatever is
        still queued behind it."""
        turn, board = wire.msg_to_board(msg)
        with self._board_lock:
            self.board = np.array(board, dtype=np.uint8)
            self.turn = int(turn)
            self.synced.set()
            for conn in self._all_conns():
                if not conn.writer_started:
                    # Mid-admit: the attach-ack must be this peer's
                    # FIRST message — _admit board-syncs it from the
                    # (just-updated) shadow right after the ack.
                    continue
                self._sync_conn_locked(conn, payload)
        tracing.event("relay.board_sync", "lifecycle", turn=turn)
        flight.note("relay.board_sync", turn=turn)

    # --- downstream fan-out ---

    def _all_conns(self) -> "list[_Conn]":
        with self._conn_lock:
            return list(self._conns)

    def _forward(self, payload: bytes, last_turn: Optional[int],
                 control: bool = False, flips: bool = False) -> None:
        """Fan one upstream frame's BYTES out (caller holds
        _board_lock — forwarding is ordered against shadow advance and
        attach syncs). Stream frames gate per peer through the PR 7
        degradation machinery; `control` frames (bye, finals) always
        enqueue; `flips` frames (fbatch, coordinate flips) skip peers
        that did not subscribe to the flip plane (a -noVis leaf wants
        alive ticks and the final, not the raster stream)."""
        conns = self._all_conns()
        if last_turn is not None:
            # The hop's half of the per-turn wire correlation: one
            # instant mark per forwarded frame, on this dump's (root-
            # corrected) timebase — `report merge --hops` differences
            # successive tiers' marks into per-hop legs.
            tracing.event("turn.forward", "wire", turn=last_turn,
                          depth=self.depth)
        self.freshness.sample((c, None) for c in conns)
        for conn in conns:
            if conn.lag_metric is not None:
                conn.lag_metric.set(conn.queued())
            if conn.drained():
                self._coalesce_resync_locked(conn)
            if not conn.synced or (
                last_turn is not None
                and last_turn <= conn.synced_turn
            ):
                continue
            if flips and not conn.want_flips:
                continue
            try:
                if not control and not conn.offer_stream():
                    continue
                conn.send_raw(payload)
                if last_turn is not None:
                    conn.note_written(last_turn)
                _METRICS.forwarded.inc()
                _METRICS.forwarded_bytes.inc(len(payload))
            except (wire.WireError, OSError):
                self._drop_conn(conn)

    def _sync_conn_locked(self, conn: _Conn, payload: bytes) -> None:
        """One downstream's BoardSync (caller holds _board_lock):
        `payload` is a ready board frame to forward byte-identically;
        None encodes one fresh frame from the shadow."""
        if payload is None:
            payload = wire.board_to_frame(self.turn, self.board, 0)
        try:
            conn.send_raw(payload)
        except (wire.WireError, OSError):
            self._drop_conn(conn)
            return
        conn.synced = True
        conn.synced_turn = self.turn
        conn.note_written(self.turn)
        conn.delta_prev = None
        conn.mark_recovered()

    def _coalesce_resync_locked(self, conn: _Conn) -> None:
        """Degraded downstream drained inside the deadline: ONE
        coalescing BoardSync from the shadow makes it whole (the PR 7
        recovery, served from relay-local state — no upstream round
        trip)."""
        conn.resync_pending = True
        self._sync_conn_locked(conn, None)

    def _accept_loop(self) -> None:
        from gol_tpu.testing import faults

        while not self._shutdown.is_set():
            try:
                sock, addr = self._listener.accept()
            except OSError:
                return
            sock = faults.wrap("server", sock)
            # Handshake on its own thread (the WS side's slow-loris
            # defence, same reasoning): HELLO_TIMEOUT deadlines each
            # recv, not the whole handshake — a byte-trickling peer
            # must wedge only its own thread, never the accept loop.
            threading.Thread(
                target=self._tcp_handshake, args=(sock, addr),
                name="gol-relay-hs", daemon=True,
            ).start()

    def _tcp_handshake(self, sock, addr) -> None:
        try:
            sock.settimeout(self.HELLO_TIMEOUT)
            hello = wire.recv_msg(sock, allow_binary=False)
            if not hello or hello.get("t") != "hello":
                raise wire.WireError(f"bad hello: {hello!r}")
        except (wire.WireError, OSError, ValueError, TimeoutError) as e:
            log.warning("relay rejecting connection from %s: %s",
                        addr, e)
            _METRICS.rejects.inc()
            with contextlib.suppress(OSError):
                sock.close()
            return
        self._admit(sock, hello)

    def _reject(self, sock, reason: str, ws: bool = False,
                **extra) -> None:
        _METRICS.rejects.inc()
        msg = {"t": "error", "reason": reason, **extra}
        with contextlib.suppress(Exception):
            if ws:
                # The peer upgraded to WebSocket: the reject must be
                # a WS message + close frame, not raw wire bytes.
                sock.sendall(wsproto.encode_frame(
                    wsproto.OP_TEXT,
                    json.dumps(msg, separators=(",", ":")).encode(),
                ) + wsproto.close_frame(1002, reason))
            else:
                wire.send_msg(sock, msg)
        sock.close()

    def _admit(self, sock, hello: dict,
               make_conn=None, reader=None) -> None:
        """Shared admission for TCP and WS downstreams; hello rules:
        authenticated, binary + want_flips (the relay forwards binary
        batch frames — it cannot re-encode for legacy peers without
        breaking the zero-re-encode invariant)."""
        is_ws = make_conn is WSConn
        if self._secret is not None and not hmac.compare_digest(
            str(hello.get("secret", "")).encode("utf-8", "replace"),
            self._secret.encode("utf-8", "replace"),
        ):
            self._reject(sock, "unauthorized", ws=is_ws)
            return
        if not hello.get("binary"):
            # The capability floor of a byte-copy tier, stated as a
            # reasoned reject — never a silent incompatible stream
            # (legacy JSON peers would need per-peer re-encoding).
            self._reject(sock, "relay-binary-only", ws=is_ws)
            return
        hb = bool(hello.get("hb", False)) and self.heartbeat_secs > 0
        # Downstream max-k is NOT negotiable below the upstream's:
        # frames arrive pre-encoded at the upstream granularity and
        # forward verbatim — the ack re-advertises that k honestly
        # (peers' parsers accept any k <= FBATCH_MAX_TURNS), and a
        # hostile "batch" value in the hello is simply ignored.
        cls = make_conn if make_conn is not None else _Conn
        # want_flips per peer: a flip-less observer (-noVis leaf) gets
        # the board sync, turn/alive events, heartbeats and the final
        # — never the raster stream it didn't subscribe to.
        conn = cls(sock, bool(hello.get("want_flips", False)),
                   binary=True, role="observe", hb=hb,
                   batch=self.upstream_batch or self.batch_turns,
                   high_water=self.high_water,
                   drain_secs=self.drain_secs, pool=self.pool)
        # Admission check AND slot reservation in ONE critical
        # section: TCP accepts and WS handshakes admit on concurrent
        # threads, and a check-then-append window would let two
        # simultaneous attaches both squeeze past max_peers - 1.
        with self._conn_lock:
            admitted = (self.max_peers is None
                        or len(self._conns) < self.max_peers)
            if admitted:
                self._conns.append(conn)
                _METRICS.peers.set(len(self._conns))
                if isinstance(conn, WSConn):
                    _METRICS.ws_peers.inc()
        if not admitted:
            _METRICS.rejects.inc()
            with contextlib.suppress(Exception):
                # Via the conn, so the error is transport-framed (a
                # WS peer must get a WS message, not raw bytes).
                conn.send({"t": "error", "reason": "at-capacity",
                           "retry_after": self.retry_after_secs})
            conn.close()
            return
        ack = {"t": "attach-ack", "clock": True, "depth": self.depth,
               "batch": conn.batch}
        if hb:
            ack["hb_secs"] = self.heartbeat_secs
        try:
            conn.send(ack)
            conn.start_writer(self._drop_conn)
        except (wire.WireError, OSError):
            self._drop_conn(conn)
            return
        install_lag_gauge(conn)
        tracing.event("relay.attach", "lifecycle", token=conn.token,
                      ws=isinstance(conn, WSConn))
        flight.note("relay.attach", token=conn.token)
        # Board sync under the lock: ordered against shadow advance —
        # a frame being forwarded concurrently can never tear it.
        with self._board_lock:
            if self.board is not None:
                self._sync_conn_locked(conn, None)
            # else: pre-sync attach — the upstream's first board frame
            # fans out to every conn, this one included.
        threading.Thread(
            target=reader if reader is not None else self._reader_loop,
            args=(conn,), name="gol-relay-reader", daemon=True,
        ).start()

    def _drop_conn(self, conn: _Conn) -> None:
        with self._conn_lock:
            removed = conn in self._conns
            if removed:
                self._conns.remove(conn)
            _METRICS.peers.set(len(self._conns))
            if removed and isinstance(conn, WSConn):
                _METRICS.ws_peers.dec()
        if removed:
            remove_lag_gauge(conn)
            self.freshness.forget(conn.token)
            _forget_peer_usage(conn)
            tracing.event("relay.detach", "lifecycle", token=conn.token)
        conn.close()

    # --- downstream control plane ---

    def _clk_reply(self, conn: _Conn, msg: dict) -> None:
        """Per-hop clock composition: echo with OUR clock plus OUR
        upstream offset — the peer's estimate lands on the ROOT's
        timebase, however deep this hop is."""
        with contextlib.suppress(wire.WireError, OSError):
            conn.send_direct({
                "t": "clk", "t0": msg.get("t0"),
                "ts": time.time() + (self.clock_offset or 0.0),
            })

    def _handle_ctl(self, conn: _Conn, msg: dict) -> bool:
        """One downstream control message; False ends the reader."""
        t = msg.get("t")
        if t == "clk":
            self._clk_reply(conn, msg)
        elif t == "repoint":
            # Control-plane heal verb (PR 18): re-point this relay's
            # upstream at a new address. Rides the ordinary downstream
            # link, so the relay-secret handshake already gates it.
            try:
                host, _, port = str(msg.get("addr", "")).rpartition(":")
                result = self.repoint((host, int(port)))
                reply = {"t": "repoint-r", "ok": True, **result}
            except (ValueError, TypeError) as e:
                reply = {"t": "repoint-r", "ok": False,
                         "reason": str(e) or "bad-addr"}
            with contextlib.suppress(Exception):
                conn.send_direct(reply)
        elif t == "key":
            if msg.get("key") == "q":
                self._drop_from_reader(conn)
                return False
            with contextlib.suppress(Exception):
                conn.send({"t": "error", "reason": "observer"})
        return True

    def _drop_from_reader(self, conn: _Conn) -> None:
        """Clean 'q' detach: farewell + bounded drain, then the ONE
        shared removal path (`_drop_conn`) does the books — two
        bookkeeping copies had already drifted once."""
        with contextlib.suppress(Exception):
            conn.send({"t": "detached"})
        conn.finish()
        self._drop_conn(conn)

    def _reader_loop(self, conn: _Conn) -> None:
        while True:
            try:
                msg = wire.recv_msg(conn.sock, allow_binary=False)
            except TimeoutError:
                if conn._dead.is_set():
                    self._drop_conn(conn)
                    return
                continue
            except (wire.WireError, OSError):
                msg = None
            if msg is None:
                self._drop_conn(conn)
                return
            conn.last_rx = time.monotonic()
            conn.hb_unanswered = 0
            if not self._handle_ctl(conn, msg):
                return

    # --- WebSocket gateway (relay.ws) ---

    def _ws_accept_loop(self) -> None:
        while not self._shutdown.is_set():
            try:
                sock, addr = self._ws_listener.accept()
            except OSError:
                return
            # Handshakes run on their own thread: a slow-loris upgrade
            # must not wedge the accept loop.
            threading.Thread(
                target=self._ws_handshake, args=(sock, addr),
                name="gol-relay-ws-hs", daemon=True,
            ).start()

    def _ws_handshake(self, sock, addr) -> None:
        try:
            sock.settimeout(self.HELLO_TIMEOUT)
            wsproto.handshake(sock)
            # First WS message must be the hello JSON.
            op, payload = wsproto.read_message(sock)
            if op not in (wsproto.OP_TEXT, wsproto.OP_BINARY) \
                    or payload is None:
                raise wsproto.WSError("expected a hello message")
            hello = json.loads(payload.decode("utf-8"))
            if not isinstance(hello, dict) \
                    or hello.get("t") != "hello":
                raise wsproto.WSError(f"bad hello: {hello!r}")
        except (wsproto.WSError, wire.WireError, OSError, ValueError,
                TimeoutError) as e:
            log.warning("ws handshake from %s failed: %s", addr, e)
            _METRICS.rejects.inc()
            with contextlib.suppress(OSError):
                sock.close()
            return
        # Browser hellos imply the binary plane (WS binary messages).
        hello.setdefault("binary", True)
        hello.setdefault("want_flips", True)
        self._admit(sock, hello, make_conn=WSConn,
                    reader=self._ws_reader_loop)

    def _ws_reader_loop(self, conn: WSConn) -> None:
        """Downstream WS reader: data messages carry the JSON control
        catalog; pings are answered, pongs refresh liveness; every
        protocol violation detaches THIS peer cleanly and nothing
        else (the fuzz sweep's pin)."""
        def on_control(op, payload):
            conn.last_rx = time.monotonic()
            conn.hb_unanswered = 0
            if op == wsproto.OP_PING:
                conn.enqueue_control(
                    wsproto.encode_frame(wsproto.OP_PONG, payload or b"")
                )

        while True:
            try:
                op, payload = wsproto.read_message(conn.sock,
                                                   on_control=on_control)
            except TimeoutError:
                if conn._dead.is_set():
                    self._drop_conn(conn)
                    return
                continue
            except (wsproto.WSError, OSError):
                with contextlib.suppress(Exception):
                    conn.enqueue_control(wsproto.close_frame(1002))
                self._drop_conn(conn)
                return
            conn.last_rx = time.monotonic()
            conn.hb_unanswered = 0
            if op == wsproto.OP_CLOSE:
                with contextlib.suppress(Exception):
                    conn.enqueue_control(wsproto.close_frame())
                self._drop_conn(conn)
                return
            if op == wsproto.OP_PING:
                conn.enqueue_control(
                    wsproto.encode_frame(wsproto.OP_PONG, payload or b"")
                )
                continue
            if op == wsproto.OP_PONG:
                continue  # the liveness refresh happened above
            try:
                msg = json.loads((payload or b"").decode("utf-8"))
            except (ValueError, UnicodeDecodeError):
                continue  # non-JSON data from a browser: ignorable
            if isinstance(msg, dict) and msg.get("t") == "hb":
                continue
            if isinstance(msg, dict):
                if not self._handle_ctl(conn, msg):
                    return

    # --- downstream liveness (the EngineServer discipline) ---

    def _heartbeat_loop(self) -> None:
        interval = max(0.05, self.heartbeat_secs / 2.0)
        while not self._shutdown.wait(interval):
            now = time.monotonic()
            conns = self._all_conns()
            self.freshness.sample((c, None) for c in conns)
            # Accounting sweep (the servers' discipline, per hop):
            # each downstream's writer backlog in frame-seconds —
            # wire bytes are already charged at the _Conn choke point.
            _meter = accounting.meter()
            if _meter is not None:
                for c in conns:
                    q = c.queued()
                    if q:
                        _meter.charge(c.principal,
                                      queue_frame_seconds=q * interval)
            for conn in conns:
                if not conn.writer_started:
                    continue
                if conn.degraded:
                    if conn.drained():
                        with self._board_lock:
                            if self.board is not None:
                                self._coalesce_resync_locked(conn)
                    elif (now - conn.degraded_since > conn.drain_secs
                          and conn.queued() > conn.LOW_WATER):
                        log.warning(
                            "evicting relay peer %d: wedged %.1fs past "
                            "the drain deadline", conn.token,
                            now - conn.degraded_since,
                        )
                        conn.count_overflow()
                        self._drop_conn(conn)
                    continue
                if (conn.hb and conn.hb_unanswered >= self.HB_MISS_LIMIT
                        and now - conn.last_rx > self.evict_secs):
                    log.warning("evicting unresponsive relay peer %d",
                                conn.token)
                    tracing.event("relay.evict", "lifecycle",
                                  token=conn.token)
                    self._drop_conn(conn)
                    continue
                if now - conn.last_tx >= self.heartbeat_secs:
                    try:
                        if isinstance(conn, WSConn):
                            conn.beacon(self.turn)
                            conn.last_tx = time.monotonic()
                        else:
                            conn.send_raw(
                                wire.heartbeat_to_frame(self.turn)
                            )
                    except Exception:
                        self._drop_conn(conn)
                        continue
                    if conn.hb:
                        conn.hb_unanswered += 1


class _UpstreamRejected(ConnectionError):
    def __init__(self, reason: str, retry_after):
        super().__init__(reason)
        self.reason = reason
        self.retry_after = retry_after
